"""Long-context attention: ring attention + Ulysses (sep) attention.

Reference: the reference ecosystem's balanced ring flash attention
(paddlenlp/transformers/ring_flash_attention.py (approx., out-of-tree)) and
the ``sep_degree`` Ulysses axis wired through
python/paddle/distributed/fleet/base/topology.py — SURVEY.md §5.7.

TPU-native design (this is where the rebuild can exceed the reference —
SURVEY.md §5.7 "TPU equivalent"):

  - **Ring attention** rides the ICI torus: each sep shard holds a Q/K/V
    sequence chunk; ``axis_size`` scan steps each compute one block of the
    online-softmax update and rotate the K/V chunk to the next neighbour
    with ``lax.ppermute`` — XLA overlaps the permute with the block matmul,
    so the sequence length per chip is bounded by HBM while communication
    stays nearest-neighbour. Backward is jax autodiff: the transpose of
    ppermute is the reverse-direction ppermute, giving the reverse ring
    without hand-written comm.
  - **Ulysses attention**: one ``lax.all_to_all`` turns seq-sharded
    activations into head-sharded ones (each shard sees the FULL sequence
    for H/P heads), runs ordinary attention, and the inverse all_to_all
    restores seq sharding. Two collectives total, both on ICI.

Both functions are PER-SHARD code (inside ``jax.shard_map`` over the sep
axis); ``sep_scaled_dot_product_attention`` is the jit-level wrapper that
builds the shard_map over the current mesh. Layout: (B, S, H, D) — the
paddle sdpa convention; S is the GLOBAL length, S/P per shard.
"""

from __future__ import annotations

import functools
import math
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


# ------------------------------------------------------------ ring attention
def _chunk_attn(q, k, v, causal, sm_scale, h, hkv):
    """One ring step's inner attention: (B, Cq, H, D) x (B, Ck, Hkv, D)
    -> (out (B, Cq, H, D), lse (B, H, Cq)), the mergeable pair. Runs the
    Pallas flash kernel (O(block) temps, unexpanded GQA kv) whenever the
    chunk shapes fit its tiling on the current backend; falls back to a
    dense-with-lse computation otherwise (small test chunks)."""
    from ....flags import is_tpu_backend, snapshot
    snap = snapshot(("use_pallas",))
    b, cq, _, d = q.shape
    ck = k.shape[1]
    if is_tpu_backend():
        # Mosaic tiling wants full lane-aligned chunks
        ok = cq % 128 == 0 and ck % 128 == 0
    else:
        # pallas INTERPRET mode cannot run inside a check_vma=True
        # shard_map (jax hlo_interpreter limitation) — only use it when
        # the values carry no vma (sep-only meshes run check_vma=False)
        ok = not jax.typeof(q).vma
    if snap.use_pallas and ok:
        from ....kernels.flash_attention import flash_attention_with_lse
        try:
            qf = jnp.swapaxes(q, 1, 2).reshape(b * h, cq, d)
            kf = jnp.swapaxes(k, 1, 2).reshape(b * hkv, ck, d)
            vf = jnp.swapaxes(v, 1, 2).reshape(b * hkv, ck, d)
            # pin 128x128 tiles: the FLAGS_flash_block_* tuning is swept
            # on monolithic multi-k seqs; ring steps see small per-rank
            # chunks where a full-chunk block would re-materialize the
            # quadratic (C, C) scores the ring exists to avoid
            out, lse = flash_attention_with_lse(
                qf, kf, vf, causal=causal, sm_scale=sm_scale,
                block_q=128, block_k=128,
                n_heads=h, n_kv_heads=hkv)
            return (jnp.swapaxes(out.reshape(b, h, cq, d), 1, 2),
                    lse.reshape(b, h, cq))
        except NotImplementedError:
            pass
    rep = h // hkv
    kx = jnp.repeat(k, rep, axis=2) if rep > 1 else k
    vx = jnp.repeat(v, rep, axis=2) if rep > 1 else v
    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * sm_scale
    kf = jnp.swapaxes(kx, 1, 2).astype(jnp.float32)
    vf = jnp.swapaxes(vx, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf)
    if causal:
        mask = lax.broadcasted_iota(jnp.int32, (cq, ck), 0) >= \
            lax.broadcasted_iota(jnp.int32, (cq, ck), 1)
        s = jnp.where(mask, s, _NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)            # (B, H, Cq)
    out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), vf)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype), lse


def _merge(o1, lse1, o2, lse2):
    """Combine two partial softmax results in log-space: out (B, C, H, D)
    returned in FLOAT32 (the ring accumulator dtype — per-step casts back
    to bf16 would compound rounding across the P merges; callers cast
    once after the scan), lse (B, H, C). Empty partials carry
    lse = -1e30 and contribute 0."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    w1 = jnp.exp(lse1 - m_safe)
    w2 = jnp.exp(lse2 - m_safe)
    den = w1 + w2
    den_safe = jnp.where(den == 0.0, 1.0, den)
    lse = jnp.where(den == 0.0, _NEG_INF, m_safe + jnp.log(den_safe))
    wt = lambda w: jnp.swapaxes(w / den_safe, 1, 2)[..., None]
    return (o1.astype(jnp.float32) * wt(w1)
            + o2.astype(jnp.float32) * wt(w2)), lse


def _empty_partial(b, c, h, d):
    return (jnp.zeros((b, c, h, d), jnp.float32),
            jnp.full((b, h, c), _NEG_INF, jnp.float32))


def ring_flash_attention(q, k, v, axis_name: str = "sep",
                         causal: bool = True,
                         sm_scale: Optional[float] = None,
                         zigzag: bool = False):
    """Per-shard ring attention. q/k/v: (B, C, H(kv), D) local chunks of
    the (B, S, H, D) global arrays, C = S / axis_size; GQA kv (Hkv < H)
    rides the ring UNEXPANDED. Returns (B, C, H, D).

    Each of the ``axis_size`` ring steps computes one chunk-vs-chunk
    attention through the Pallas flash kernel (mergeable (out, lse) form
    — per-shard temps O(C*D + block^2), never the (C, C) score matrix)
    and rotates the kv chunk to the neighbour with ``lax.ppermute``; XLA
    overlaps the permute with the step's matmuls, and the backward ring
    is the transposed ppermute via autodiff.

    ``zigzag`` (opt-in — the data must actually BE in zigzag order; the
    function cannot reorder it): the caller feeds chunks where rank r
    holds sequence pieces r and 2P-1-r (half a chunk each;
    ``sep_scaled_dot_product_attention`` does the reorder and sets this).
    Causal work then balances EXACTLY: per rank over a full rotation,
    qa-vs-ka runs r full half-blocks, qb-vs-ka runs P (piece(qb) =
    2P-1-r exceeds every ka piece, so it is a full half-block on all P
    steps), qb-vs-kb runs P-1-r — a constant 2P-1 halves plus the
    diagonal contributions (qa-vs-ka and qb-vs-kb at src == r), vs the
    contiguous layout's r-proportional skew (rank P-1 does P times rank
    0's work). Work units are gated by ``lax.switch`` on the piece
    comparison, so skipped blocks cost nothing; the branches are pure
    local compute (no collectives), so per-rank divergence is sound."""
    p = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, c, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} not divisible by kv heads {hkv}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    perm = [(j, (j + 1) % p) for j in range(p)]

    def rotate(t):
        return lax.ppermute(t, axis_name, perm)

    def _vary(x):
        # fresh accumulators start unvarying and need the varying tag for
        # the scan carry; never applied to k/v (already varying — under
        # check_vma=False their typeof may not even report it)
        if axis_name in jax.typeof(x).vma:
            return x
        return lax.pcast(x, axis_name, to="varying")

    def unit(mode, qx, kx, vx):
        """mode 0: skip, 1: full, 2: causal (same-piece, aligned). The o
        partial comes back f32 (switch branches must agree with the skip
        branch's f32 accumulator dtype)."""

        def attn(causal_):
            def run(a, b_, c_):
                o, lse = _chunk_attn(a, b_, c_, causal_, sm_scale, h, hkv)
                return o.astype(jnp.float32), lse
            return run

        return lax.switch(
            mode,
            [lambda a, b_, c_: jax.tree.map(_vary, _empty_partial(
                b, a.shape[1], h, d)),
             attn(False), attn(True)],
            qx, kx, vx)

    if not zigzag:
        # one accumulator over the whole chunk. Non-causal: every chunk
        # pair runs full. Causal contiguous: rank r's chunk attends
        # chunks src < r fully, its own causally, later ones not at all
        # (work skewed by r — the zigzag layout fixes that).
        def step(carry, i):
            o, lse, k_cur, v_cur = carry
            src = (idx - i) % p
            if causal:
                mode = jnp.where(src == idx, 2,
                                 jnp.where(src < idx, 1, 0))
            else:
                mode = jnp.ones((), jnp.int32)
            oi, lsei = unit(mode.astype(jnp.int32), q, k_cur, v_cur)
            o, lse = _merge(o, lse, oi, lsei)
            return (o, lse, rotate(k_cur), rotate(v_cur)), None

        o0, l0 = _empty_partial(b, c, h, d)
        carry = (_vary(o0), _vary(l0), k, v)
        (o, _, _, _), _ = lax.scan(step, carry, jnp.arange(p))
        return o.astype(q.dtype)

    # zigzag: local chunk = [piece idx, piece 2P-1-idx], half each
    if not causal:
        raise ValueError("zigzag layout only applies to causal attention")
    if c % 2:
        raise ValueError(f"zigzag ring needs an even local chunk, got {c}")
    half = c // 2
    qa, qb = q[:, :half], q[:, half:]

    def step(carry, i):
        oa, la, ob, lb, k_cur, v_cur = carry
        src = (idx - i) % p
        ka, kb = k_cur[:, :half], k_cur[:, half:]
        va, vb = v_cur[:, :half], v_cur[:, half:]
        # piece indices: qa=idx, qb=2P-1-idx, ka=src, kb=2P-1-src
        mode_aa = jnp.where(src == idx, 2,
                            jnp.where(src < idx, 1, 0)).astype(jnp.int32)
        # piece(ka)=src <= P-1 < P <= 2P-1-idx = piece(qb): always full
        o1, l1 = unit(mode_aa, qa, ka, va)
        o2, l2 = _chunk_attn(qb, ka, va, False, sm_scale, h, hkv)
        mode_bb = jnp.where(src == idx, 2,
                            jnp.where(src > idx, 1, 0)).astype(jnp.int32)
        o3, l3 = unit(mode_bb, qb, kb, vb)
        oa, la = _merge(oa, la, o1, l1)
        ob, lb = _merge(ob, lb, o2, l2)
        ob, lb = _merge(ob, lb, o3, l3)
        return (oa, la, ob, lb, rotate(k_cur), rotate(v_cur)), None

    oa0, la0 = _empty_partial(b, half, h, d)
    ob0, lb0 = _empty_partial(b, half, h, d)
    carry = (_vary(oa0), _vary(la0), _vary(ob0), _vary(lb0), k, v)
    (oa, _, ob, _, _, _), _ = lax.scan(step, carry, jnp.arange(p))
    return jnp.concatenate([oa, ob], axis=1).astype(q.dtype)


def zigzag_order(S: int, p: int):
    """Global sequence permutation for the balanced ring: rank r's chunk
    is [piece r, piece 2P-1-r] of 2P equal pieces. Returns (order,
    inverse) index arrays, or None when S doesn't split into 2P pieces."""
    if S % (2 * p):
        return None
    piece = S // (2 * p)
    order = np.concatenate([
        np.r_[r * piece:(r + 1) * piece,
              (2 * p - 1 - r) * piece:(2 * p - r) * piece]
        for r in range(p)])
    inv = np.argsort(order)
    return order, inv


# --------------------------------------------------------- ulysses attention
def _dense_sdpa(q, k, v, causal, sm_scale):
    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * sm_scale
    kf = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vf = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = lax.broadcasted_iota(jnp.int32, (sq, sk), 0) >= \
            lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(mask, s, _NEG_INF)
    o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vf)
    return jnp.swapaxes(o.astype(q.dtype), 1, 2)


def ulysses_attention(q, k, v, axis_name: str = "sep", causal: bool = True,
                      sm_scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None,
                      attn_fn_gqa: bool = False):
    """Per-shard Ulysses attention (reference: the sep_degree axis /
    head-scatter seq-gather all-to-alls). q/k/v: (B, C, H, D) seq-sharded;
    requires H % axis_size == 0. Each shard computes FULL-sequence attention
    for H/P heads, so any single-device attention impl (the Pallas flash
    kernel included) drops in via ``attn_fn``.

    GQA (k/v with Hkv < H heads): when Hkv is divisible by the sep degree
    the kv all-to-alls split kv heads like q heads. When it is NOT
    (Hkv < P, the 70B-style layout), plain Ulysses cannot shard kv by
    head — instead the (few) kv heads are ALL-GATHERED in sequence and
    each shard selects the kv heads its q-head slice attends to
    (comm: 2 q all-to-alls + one kv all-gather of B*S*Hkv*D — cheaper
    than ring's (P-1) kv rotations whenever Hkv <= 2H/P).

    ``attn_fn_gqa``: declare that ``attn_fn`` handles grouped-query inputs
    natively (fewer kv heads than q heads, e.g. the Pallas flash kernel) —
    the unexpanded kv then reaches it at Hkv bandwidth instead of being
    jnp.repeat-expanded first (advisor r3)."""
    p = lax.axis_size(axis_name)
    b, c, h, d = q.shape
    hkv = k.shape[2]
    if h % p:
        raise ValueError(f"num heads {h} not divisible by sep degree {p}")
    if h % hkv:
        raise ValueError(f"q heads {h} not divisible by kv heads {hkv}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    def seq_gather(t):   # (B, C, Hx, D) -> (B, C*P, Hx/P, D)
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def seq_scatter(t):  # (B, C*P, H/P, D) -> (B, C, H, D)
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg = seq_gather(q)
    fn = attn_fn or functools.partial(_dense_sdpa, causal=causal,
                                      sm_scale=sm_scale)
    gqa_fn = attn_fn is not None and attn_fn_gqa
    if hkv == h or hkv % p == 0:
        kg, vg = seq_gather(k), seq_gather(v)
        if hkv != h and not gqa_fn:
            # per-shard GQA: expand the local kv head slice to match
            # (dense fallback only — a GQA-aware attn_fn reads the
            # unexpanded slice at Hkv bandwidth)
            rep = (h // p) // (hkv // p)
            kg = jnp.repeat(kg, rep, axis=2)
            vg = jnp.repeat(vg, rep, axis=2)
        out = fn(qg, kg, vg)
    else:
        # GQA-Ulysses: kv heads are too few to split — gather full-seq kv
        # and select this shard's group heads (q head g = r*(H/P)+j maps
        # to kv head g // (H/Hkv))
        kg = lax.all_gather(k, axis_name, axis=1, tiled=True)
        vg = lax.all_gather(v, axis_name, axis=1, tiled=True)
        r = lax.axis_index(axis_name)
        rep = h // hkv
        hq_l = h // p
        # here hkv % p != 0 (else-branch), which rules out hq_l % rep == 0
        # (they are equivalent) — the only unexpanded-kv case left is the
        # whole local q slice sharing ONE kv group:
        if gqa_fn and rep % hq_l == 0:
            # the whole local q slice lives inside ONE kv group (slice
            # start r*hq_l is a multiple of hq_l and rep % hq_l == 0, so
            # the slice never crosses a group boundary): one kv head
            kv_heads = jnp.reshape(r * hq_l // rep, (1,))
            out = fn(qg, jnp.take(kg, kv_heads, axis=2),
                     jnp.take(vg, kv_heads, axis=2))
        else:
            heads = r * (h // p) + jnp.arange(h // p)
            k_sel = jnp.take(kg, heads // rep, axis=2)
            v_sel = jnp.take(vg, heads // rep, axis=2)
            out = fn(qg, k_sel, v_sel)
    return seq_scatter(out)


# ------------------------------------------------------------- jit-level API
def sep_scaled_dot_product_attention(
        q, k, v, mesh: Optional[Mesh] = None, sep_axis: str = "sep",
        method: str = "ring", causal: bool = True,
        sm_scale: Optional[float] = None):
    """Context-parallel sdpa at the jit level: shard_maps the per-shard
    implementation over ``sep_axis`` (other mesh axes stay under GSPMD).
    q/k/v: GLOBAL (B, S, H, D); S must divide by the sep degree."""
    if mesh is None:
        from ..base_topology import get_hybrid_communicate_group
        mesh = get_hybrid_communicate_group().get_mesh()
    if sep_axis not in mesh.shape or mesh.shape[sep_axis] <= 1:
        if k.shape[2] != q.shape[2]:      # GQA: the dense path expands
            rep = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        return _dense_sdpa(q, k, v, causal,
                           sm_scale or 1.0 / math.sqrt(q.shape[-1]))

    p = mesh.shape[sep_axis]
    impl = {"ring": ring_flash_attention, "ulysses": ulysses_attention}[method]
    kw = {}
    zig = None
    if method == "ring" and causal:
        # balanced causal ring: permute the sequence into zigzag order
        # (rank r holds pieces r and 2P-1-r) so per-rank causal work is
        # uniform; the inverse permute restores order on the way out.
        # GSPMD turns the takes on the seq-sharded operands into the
        # half-chunk exchange.
        zig = zigzag_order(q.shape[1], p)
        kw["zigzag"] = zig is not None
    fn = functools.partial(impl, axis_name=sep_axis, causal=causal,
                           sm_scale=sm_scale, **kw)
    spec = P(None, sep_axis, None, None)
    if set(mesh.axis_names) == {sep_axis}:
        # full-manual mesh: check_vma=False — pallas interpret mode can
        # then serve the inner flash kernel on CPU test meshes
        mapped = jax.shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
    else:
        # manual over sep only; other axes stay GSPMD. check_vma must be
        # True: this jax version's check_vma=False path re-enters
        # shard_map with out_specs over ALL mesh axes, which
        # partial-manual mode rejects
        mapped = jax.shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names=frozenset({sep_axis}))
    if zig is not None:
        order, inv = zig
        out = mapped(jnp.take(q, order, axis=1),
                     jnp.take(k, order, axis=1),
                     jnp.take(v, order, axis=1))
        return jnp.take(out, inv, axis=1)
    return mapped(q, k, v)
