"""reference: python/paddle/distributed/fleet/utils/fs.py — LocalFS /
HDFSClient + UtilBase. LocalFS is fully functional; HDFS needs a
cluster client binary this image doesn't ship, so HDFSClient raises
with guidance at USE (construction is allowed for config-parity)."""

from __future__ import annotations

import os
import shutil


class LocalFS:
    def ls_dir(self, path):
        dirs, files = [], []
        for n in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, n))
             else files).append(n)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def rename(self, src, dst):
        os.rename(src, dst)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def need_upload_download(self) -> bool:
        return False

    def is_file(self, path) -> bool:
        return os.path.isfile(path)

    def is_dir(self, path) -> bool:
        return os.path.isdir(path)

    def is_exist(self, path) -> bool:
        return os.path.exists(path)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def mv(self, src, dst, overwrite=False):
        if overwrite:
            self.delete(dst)
        shutil.move(src, dst)

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


class HDFSClient:
    """API-parity shell: every operation raises — no HDFS client binary
    ships in this image. Point checkpoint paths at local/NFS storage
    (LocalFS) or GCS via gcsfuse mounts instead."""

    def __init__(self, hadoop_home=None, configs=None, *a, **k):
        self._reason = (
            "HDFS is unavailable in the TPU deployment (no hadoop "
            "client); use LocalFS paths or a mounted object store")

    def __getattr__(self, name):
        def _raise(*a, **k):
            raise RuntimeError(f"HDFSClient.{name}: {self._reason}")
        return _raise


class UtilBase:
    """reference fleet.UtilBase — filesystem + barrier helpers."""

    def __init__(self):
        self._fs = LocalFS()

    def get_file_shard(self, files):
        return list(files)

    def all_gather(self, obj, comm_world="worker"):
        return [obj]

    def all_reduce(self, obj, mode="sum", comm_world="worker"):
        return obj

    def barrier(self, comm_world="worker"):
        pass
