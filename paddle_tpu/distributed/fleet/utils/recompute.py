"""Activation recompute (gradient checkpointing) user API.

Reference: python/paddle/distributed/fleet/recompute/recompute.py
(``fleet.utils.recompute(function, *args)``) — forward runs without storing
intermediate activations; backward re-runs the forward to regenerate them,
with RNG state replayed so dropout masks match.

TPU-native design: the whole mechanism is ``jax.checkpoint`` around a pure
function of (params, inputs). Under jit, XLA sees the remat annotation and
trades FLOPs for HBM exactly like the reference's 1F1B activation story;
in eager mode the taped vjp holds only the inputs and re-traces the forward
at backward time. RNG replay is structural: eager random ops split the
global key at TRACE time, so the key is a constant inside the checkpointed
jaxpr and the recomputed forward reuses it — no state save/restore dance.
"""

from __future__ import annotations

from typing import Any

import jax

from ....core.tensor import Tensor, apply_op
from ....jit import functional_call
from ....nn.layer import Layer

__all__ = ["recompute"]


def recompute(function, *args, use_reentrant: bool = True,
              preserve_rng_state: bool = True, **kwargs):
    """Run ``function(*args)`` under activation recompute.

    ``function`` may be a Layer (or a Layer's bound method): its parameters
    join the differentiable inputs, so param grads flow. Plain functions of
    Tensors work too (their closed-over Tensors are treated as constants,
    matching the reference's documented contract)."""
    if kwargs.pop("**kwargs", None):  # pragma: no cover - defensive
        raise TypeError("unexpected kwargs")

    layer = None
    method = None
    if isinstance(function, Layer):
        layer = function
    elif hasattr(function, "__self__") and isinstance(function.__self__,
                                                      Layer):
        layer = function.__self__
        method = function.__name__

    if layer is None:
        def pure(*vals):
            inner = jax.checkpoint(lambda *v: _call_plain(function, v, kwargs))
            return inner(*vals)
        return apply_op("recompute", pure, *args)

    named = [(k, p) for k, p in layer.named_parameters()
             if not p.stop_gradient]
    keys = [k for k, _ in named]
    params = [p for _, p in named]
    frozen = {k: p._value for k, p in layer.named_parameters()
              if p.stop_gradient}
    buffers = {k: (b._value if b is not None else None)
               for k, b in layer.named_buffers()}
    buffers.update(frozen)
    n = len(params)

    def pure(*vals):
        pvals, avals = vals[:n], vals[n:]

        def fwd(pv, av):
            pdict = dict(zip(keys, pv))
            return functional_call(layer, pdict, *av, buffers=buffers,
                                   method=method, **kwargs)

        return jax.checkpoint(fwd)(pvals, avals)

    return apply_op("recompute", pure, *params, *args)


def _call_plain(function, vals, kwargs):
    from ....core import autograd
    from ....jit import tree_to_tensors, tree_to_values
    with autograd.functional_guard():
        out = function(*tree_to_tensors(vals), **kwargs)
    return tree_to_values(out)
