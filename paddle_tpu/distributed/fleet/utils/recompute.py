"""Activation recompute (gradient checkpointing) user API.

Reference: python/paddle/distributed/fleet/recompute/recompute.py
(``fleet.utils.recompute(function, *args)``) — forward runs without storing
intermediate activations; backward re-runs the forward to regenerate them,
with RNG state replayed so dropout masks match.

TPU-native design: the whole mechanism is ``jax.checkpoint`` around a pure
function of (params, inputs). Under jit, XLA sees the remat annotation and
trades FLOPs for HBM exactly like the reference's 1F1B activation story;
in eager mode the taped vjp holds only the inputs and re-traces the forward
at backward time. RNG replay is structural: eager random ops split the
global key at TRACE time, so the key is a constant inside the checkpointed
jaxpr and the recomputed forward reuses it — no state save/restore dance.
"""

from __future__ import annotations

import numpy as np

import jax

from ....core.tensor import Tensor, apply_op
from ....jit import functional_call
from ....nn.layer import Layer

__all__ = ["recompute"]


def _split_static(args):
    """Partition positional args into traced data (Tensors/arrays) and
    static Python values (bools, ints, None, ...). The reference passes
    non-tensor args through unchanged — a bool flag must stay a Python bool
    inside the checkpointed forward, not become a tracer."""
    dyn_idx, dyn, template = [], [], list(args)
    for i, a in enumerate(args):
        if isinstance(a, (Tensor, jax.Array, np.ndarray)):
            dyn_idx.append(i)
            dyn.append(a)
            template[i] = None
    return dyn_idx, dyn, template


def _merge(template, dyn_idx, vals):
    full = list(template)
    for i, v in zip(dyn_idx, vals):
        full[i] = v
    return full


def recompute(function, *args, use_reentrant: bool = True,
              preserve_rng_state: bool = True, **kwargs):
    """Run ``function(*args)`` under activation recompute.

    ``function`` may be a Layer (or a Layer's bound method): its parameters
    join the differentiable inputs, so param grads flow. Plain functions of
    Tensors work too (their closed-over Tensors are treated as constants,
    matching the reference's documented contract). Non-Tensor positional
    args (flags, masks-as-None, ...) pass through as static values."""
    for k, v in kwargs.items():
        if isinstance(v, Tensor) and not v.stop_gradient:
            raise TypeError(
                f"recompute() keyword argument {k!r} is a trainable Tensor; "
                "kwargs are treated as constants (no grad flows). Pass it "
                "positionally instead — matching the reference, which "
                "rejects tensor kwargs in reentrant mode.")

    layer = None
    method = None
    if isinstance(function, Layer):
        layer = function
    elif hasattr(function, "__self__") and isinstance(function.__self__,
                                                      Layer):
        layer = function.__self__
        method = function.__name__

    dyn_idx, dyn, template = _split_static(args)
    # forward may return an arbitrary pytree (e.g. (hidden, cache) or a
    # dict); apply_op only wraps flat outputs, so flatten inside the traced
    # fn and unflatten the wrapped Tensors afterwards.
    treedef_cell = []

    def _flat(out):
        leaves, treedef = jax.tree_util.tree_flatten(out)
        treedef_cell.append(treedef)
        return leaves[0] if len(leaves) == 1 else tuple(leaves)

    def _unflat(result):
        treedef = treedef_cell[-1]
        leaves = [result] if treedef.num_leaves == 1 else list(result)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    if layer is None:
        def pure(*vals):
            def fwd(*v):
                return _call_plain(function, _merge(template, dyn_idx, v),
                                   kwargs)
            return _flat(jax.checkpoint(fwd)(*vals))
        return _unflat(apply_op("recompute", pure, *dyn))

    named = [(k, p) for k, p in layer.named_parameters()
             if not p.stop_gradient]
    keys = [k for k, _ in named]
    ptensors = [p for _, p in named]  # Tensors: eager grads flow back
    _, buffers = layer.raw_state()  # frozen params merged into buffers
    n = len(ptensors)

    def pure(*vals):
        pvals, avals = vals[:n], vals[n:]

        def fwd(pv, av):
            pdict = dict(zip(keys, pv))
            return functional_call(
                layer, pdict, *_merge(template, dyn_idx, av),
                buffers=buffers, method=method, **kwargs)

        return _flat(jax.checkpoint(fwd)(pvals, avals))

    return _unflat(apply_op("recompute", pure, *ptensors, *dyn))


def _call_plain(function, vals, kwargs):
    from ....core import autograd
    from ....jit import tree_to_tensors, tree_to_values
    with autograd.functional_guard():
        out = function(*tree_to_tensors(vals), **kwargs)
    return tree_to_values(out)
