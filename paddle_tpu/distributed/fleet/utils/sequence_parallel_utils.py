"""Megatron sequence parallelism (SP) utilities.

Reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py
(``ScatterOp``/``GatherOp``/``AllGatherOp``/``ReduceScatterOp`` autograd
functions; ``ColumnSequenceParallelLinear``/``RowSequenceParallelLinear``;
``mark_as_sequence_parallel_parameter`` +
``register_sequence_parallel_allreduce_hooks``).

Two realisations:

* **Explicit (shard_map)** — the ``*Op`` functions below are per-shard
  collective pairs (fwd/bwd mirroring the reference exactly) for code that
  runs inside ``jax.shard_map``. Convention: dim 0 is the sequence dim
  (the reference uses [s, b, h] layout in SP regions).
* **GSPMD** — the ``*SequenceParallelLinear`` layers annotate activations:
  seq-sharded outside matmuls, hidden-sharded inside; XLA inserts the
  all-gather/reduce-scatter transitions these ops hand-code. LayerNorm-param
  grad sync (the reference's allreduce hooks) is automatic under GSPMD —
  the partitioner sums replicated-param grads across the mesh — so
  ``register_sequence_parallel_allreduce_hooks`` only needs to act on the
  eager path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ....nn import functional as F
from ..layers.mpu import mp_ops
from ..meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, shard_constraint,
)

# ------------------------------------------------------------- explicit ops
# (inside shard_map over the mp axis; dim 0 = sequence)

def scatter(x, axis_name="mp"):
    """fwd: keep my seq slice / bwd: all-gather (reference ScatterOp)."""
    return mp_ops._c_split(x, axis_name, 0)


def all_gather(x, axis_name="mp"):
    """fwd: all-gather seq / bwd: reduce-scatter (reference AllGatherOp)."""
    return mp_ops._all_gather(x, axis_name, 0)


def gather(x, axis_name="mp"):
    """fwd: all-gather seq; bwd: jax's native adjoint (reduce-scatter).

    The reference GatherOp declares a slice-backward — valid under its
    per-rank autodiff convention where every rank holds the full output
    cotangent. shard_map uses global-cotangent semantics (a replicated
    output's seed is split 1/n per shard), under which the reduce-scatter
    adjoint reproduces exactly the reference's composite numerics and a
    hand-coded slice-bwd would shrink grads by the axis size (see
    test_scatter_gather_roundtrip_and_grads)."""
    return mp_ops._c_concat(x, axis_name, 0)


def reduce_scatter(x, axis_name="mp"):
    """fwd: reduce-scatter seq / bwd: all-gather (reference ReduceScatterOp)."""
    return mp_ops._reduce_scatter(x, axis_name, 0)


class ScatterOp:
    apply = staticmethod(scatter)


class GatherOp:
    apply = staticmethod(gather)


class AllGatherOp:
    apply = staticmethod(all_gather)


class ReduceScatterOp:
    apply = staticmethod(reduce_scatter)


# --------------------------------------------------------------- GSPMD path
class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Column-parallel linear whose input arrives seq-sharded: the implicit
    transition is all-gather(seq) in, out-dim-sharded result out."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         has_bias=has_bias, gather_output=gather_output,
                         fuse_matmul_bias=fuse_matmul_bias, mp_group=mp_group,
                         name=name)

    def forward(self, x):
        # input: [s, b, h] sharded on s → constrain, then the matmul's GSPMD
        # solution is allgather(s) + shard(out-dim)
        spec = [self.axis] + [None] * (len(x.shape) - 1)
        x = shard_constraint(x, P(*spec))
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Row-parallel linear whose output leaves seq-sharded: the implicit
    transition is reduce-scatter(seq) instead of allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         has_bias=has_bias, input_is_parallel=input_is_parallel,
                         fuse_matmul_bias=fuse_matmul_bias, mp_group=mp_group,
                         name=name)

    def forward(self, x):
        if self.input_is_parallel:
            spec = [None] * (len(x.shape) - 1) + [self.axis]
            x = shard_constraint(x, P(*spec))
        out = F.linear(x, self.weight, self.bias)
        # output seq-sharded: GSPMD lowers the partial-sum + constraint to a
        # reduce-scatter over mp (the SP win vs plain allreduce)
        spec = [self.axis] + [None] * (len(out.shape) - 1)
        return shard_constraint(out, P(*spec))


# ------------------------------------------------------------------- hooks
def mark_as_sequence_parallel_parameter(parameter) -> None:
    """Tag params living in SP regions (LayerNorm scale/bias): the reference
    allreduces their grads over mp because each rank sees only a seq shard."""
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter) -> bool:
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(layer, accumulation_steps: int = 1,
                                               fuse_allreduce: bool = False):
    """API-parity no-op. The reference allreduces marked params' grads over
    mp because each rank differentiates only its sequence shard. Here grads
    are already global: the jitted GSPMD step's partitioner sums
    replicated-param grads across the mesh, and the eager single-controller
    tape differentiates the full (unsharded) arrays. ``accumulation_steps``/
    ``fuse_allreduce`` are accepted for signature parity only."""
    return None
