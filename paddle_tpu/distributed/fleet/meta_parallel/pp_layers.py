"""Pipeline layer segmentation: ``LayerDesc`` / ``SharedLayerDesc`` /
``PipelineLayer``.

Reference: python/paddle/distributed/fleet/meta_parallel/pp_layers (approx.
path; see SURVEY.md §2.2 "meta_parallel: PP"). The reference builds ONLY the
local stage's layers per rank and moves activations with NCCL p2p. On TPU we
are single-controller/SPMD: the PipelineLayer materializes the FULL model
(so the eager path, ``state_dict`` and parity tests work unchanged), and the
pipelined schedule (pipeline_parallel.py) stacks the uniform middle region
of identical blocks along a leading stage axis sharded over the ``pp`` mesh
axis — stage-to-stage transfer lowers to an XLA collective-permute over ICI
instead of send_v2/recv_v2.

Segmentation semantics follow the reference:
  - ``seg_method="uniform"``: split all layers into ``num_stages`` nearly
    equal runs.
  - ``seg_method="layer:Name"``: count only layers whose class name matches
    ``Name``; distribute those evenly; unmatched prefix/suffix layers attach
    to the first/last stage (how the reference keeps embedding on stage 0
    and the head on the last stage).
``SharedLayerDesc`` reproduces tied embeddings: descs with the same ``key``
share ONE layer instance; later occurrences call ``forward_func`` on the
shared instance, and because the parameter object is literally shared, the
gradient contributions sum automatically under jax autodiff (the reference
needs an explicit allreduce between the owning stages).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ....nn.layer import Layer


class LayerDesc:
    """Deferred layer construction: class + ctor args (reference class of
    the same name)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError(
                f"LayerDesc expects a Layer subclass, got {layer_func!r}")

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """A LayerDesc whose built instance is shared across all descs with the
    same ``key`` (tied embeddings)."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class _SharedCall:
    """Run-function entry for a non-owning SharedLayerDesc occurrence."""

    def __init__(self, layer: Layer, forward_func: Optional[Callable],
                 key: str):
        self.layer = layer
        self.forward_func = forward_func
        self.key = key

    def __call__(self, *args):
        if self.forward_func is not None:
            return self.forward_func(self.layer, *args)
        return self.layer(*args)


class SegmentLayers:
    """Compute stage boundaries (reference class of the same name)."""

    def __init__(self, layers_desc: Sequence, num_parts: int,
                 method: str = "uniform"):
        self._layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method
        if len(layers_desc) < num_parts:
            raise ValueError(
                f"cannot split {len(layers_desc)} layers into {num_parts} stages")

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self.uniform(len(self._layers_desc), self.num_parts)
        m = re.match(r"layer:(.+)", self.method)
        if m:
            name = m.group(1)
            matched = [i for i, d in enumerate(self._layers_desc)
                       if self._class_name(d) == name]
            if len(matched) < self.num_parts:
                raise ValueError(
                    f"{len(matched)} layers match {name!r}, need >= "
                    f"{self.num_parts} for {self.num_parts} stages")
            # distribute matched layers evenly; boundary = first matched
            # layer of each group (stage 0 additionally takes the prefix)
            per = self.uniform(len(matched), self.num_parts)
            parts = [0]
            for g in range(1, self.num_parts):
                parts.append(matched[per[g]])
            parts.append(len(self._layers_desc))
            return parts
        raise ValueError(f"unknown seg_method {self.method!r}")

    @staticmethod
    def _class_name(desc) -> str:
        if isinstance(desc, LayerDesc):
            return desc.layer_func.__name__
        return type(desc).__name__

    @staticmethod
    def uniform(num_items: int, num_parts: int) -> List[int]:
        splits = np.array_split(np.arange(num_items), num_parts)
        parts = [0]
        for s in splits:
            parts.append(parts[-1] + len(s))
        return parts


class PipelineLayer(Layer):
    """The segmented model container.

    ``layers`` is a list of Layer / LayerDesc / SharedLayerDesc / plain
    callables (parameterless transforms). All entries are materialized (the
    TPU build is single-controller); ``forward`` runs the full stack — the
    serial/eager reference path. The pipelined fast path lives in
    ``PipelineParallel``/``PipelineTrainStep``, which consume:

      - ``stack_region()``: the maximal run [start, end) of entries with
        identical parameter structure — the region that is stacked over the
        ``pp`` mesh axis; and
      - ``shared_groups``: tied-parameter aliases.
    """

    def __init__(self, layers, num_stages: Optional[int] = None,
                 topology=None, loss_fn: Optional[Callable] = None,
                 seg_method: str = "uniform", recompute_interval: int = 0,
                 recompute_ctx: Optional[Dict] = None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        self.recompute_interval = recompute_interval
        # reference arg of the same name: chunks per device for the
        # interleaved schedule (consumed by PipelineTrainStep as
        # virtual_pp_degree)
        self.num_virtual_pipeline_stages = int(num_virtual_pipeline_stages
                                               or 1)
        if num_stages is None and topology is None:
            raise ValueError("need num_stages or topology")
        if num_stages is None:
            num_stages = topology.get_pipe_parallel_world_size()
        self._num_stages = int(num_stages)
        self._layers_desc = list(layers)

        # ---- build: materialize every desc; share instances by key
        self.shared_layers: Dict[str, Layer] = {}
        self.shared_weight_attrs: Dict[str, str] = {}
        # maps run_function index -> shared key for non-owning occurrences
        self._shared_uses: Dict[int, str] = {}
        # maps shared key -> run_function index that REGISTERED the instance
        # (where its params live in the flat param dict)
        self._shared_owner_idx: Dict[str, int] = {}
        self.run_function: List[Any] = []
        for idx, d in enumerate(self._layers_desc):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self.shared_layers:
                    layer = d.build_layer()
                    self.shared_layers[d.layer_name] = layer
                    self.shared_weight_attrs[d.layer_name] = d.shared_weight_attr
                    self.add_sublayer(str(idx), layer)
                    self._shared_owner_idx[d.layer_name] = idx
                    if d.forward_func is None:
                        self.run_function.append(layer)
                    else:
                        self.run_function.append(
                            _SharedCall(layer, d.forward_func, d.layer_name))
                        self._shared_uses[idx] = d.layer_name
                else:
                    layer = self.shared_layers[d.layer_name]
                    self.run_function.append(
                        _SharedCall(layer, d.forward_func, d.layer_name))
                    self._shared_uses[idx] = d.layer_name
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
                self.add_sublayer(str(idx), layer)
                self.run_function.append(layer)
            elif isinstance(d, Layer):
                self.add_sublayer(str(idx), d)
                self.run_function.append(d)
            elif callable(d):
                self.run_function.append(d)
            else:
                raise TypeError(f"unsupported pipeline entry {d!r}")

        # ---- segment
        # segment_parts drives describe()/get_stage_range() metadata AND
        # the executed stage split: PipelineTrainStep honors the per-stage
        # block counts via stage_block_counts() — uneven counts run as a
        # padded stacked scan with per-stage masks (VERDICT r4 item 4).
        # Under the interleaved schedule (V > 1) contiguous segment_parts
        # don't apply; get_stage_layer_indices() is the placement source
        # of truth there.
        self.segment_parts = SegmentLayers(
            self._layers_desc, self._num_stages, seg_method).do_segment()
        self._seg_method = seg_method

    # ---------------------------------------------------------------- eager
    def forward(self, *args):
        out = args
        for fn in self.run_function:
            out = fn(*out) if isinstance(out, tuple) else fn(out)
            if not isinstance(out, tuple):
                out = (out,)
        return out[0] if len(out) == 1 else out

    # ------------------------------------------------------------- metadata
    def get_num_stages(self) -> int:
        return self._num_stages

    def get_stage_range(self, stage: int):
        if self.num_virtual_pipeline_stages > 1:
            raise ValueError(
                "get_stage_range() assumes one contiguous range per stage; "
                "with num_virtual_pipeline_stages > 1 device placement is "
                "interleaved — use get_stage_layer_indices(stage) instead")
        return self.segment_parts[stage], self.segment_parts[stage + 1]

    def get_stage_layer_indices(self, stage: int):
        """run_function indices held by ``stage``. Under the interleaved
        schedule (num_virtual_pipeline_stages = V > 1) device s holds depth
        chunks {s, s+S, ...} of the stacked block region, plus the
        replicated prefix/suffix entries."""
        V, S = self.num_virtual_pipeline_stages, self._num_stages
        if V == 1:
            a, b = self.get_stage_range(stage)
            return list(range(a, b))
        start, end = self.stack_region()
        n = end - start
        L = n // (S * V)
        idxs = list(range(0, start)) if stage == 0 else []
        for v in range(V):
            c0 = start + (v * S + stage) * L
            idxs.extend(range(c0, c0 + L))
        if stage == S - 1:
            idxs.extend(range(start + S * V * L, len(self.run_function)))
        return idxs

    def get_stage_layers(self, stage: int):
        return [self.run_function[i]
                for i in self.get_stage_layer_indices(stage)]

    def _param_signature(self, entry) -> Optional[tuple]:
        """Structure key for stackability: relative param names+shapes+dtypes.
        None for non-Layer entries and shared uses (never stackable)."""
        if not isinstance(entry, Layer) or isinstance(entry, _SharedCall):
            return None
        sig = tuple(sorted(
            (name, tuple(p.shape), str(p.dtype))
            for name, p in entry.named_parameters()))
        return sig if sig else None

    def stage_block_counts(self) -> List[int]:
        """Per-stage count of stack-region blocks implied by
        ``seg_method``: stage ``s`` executes the blocks whose desc index
        falls in ``[segment_parts[s], segment_parts[s+1]) ∩
        stack_region``. Entries outside the region (embedding, final
        norm, head, reshapes) run replicated on every device regardless
        of boundaries — the SPMD collapse of the reference's stage
        placement for non-block layers (reference honours them via NCCL
        p2p placement; here they are not pipelined at all).

        ``"uniform"`` therefore distributes the BLOCK REGION uniformly
        rather than intersecting boundaries computed over all descs:
        under the collapse only blocks carry stage load, so counting the
        replicated prefix/suffix against stage 0 / S-1 (as a literal
        intersection would) manufactures skew — e.g. [3, 1] where the
        even [2, 2] exists — that the reference's placement semantics
        never intended."""
        import numpy as _np
        start, end = self.stack_region()
        if self._seg_method == "uniform":
            return [len(s) for s in
                    _np.array_split(_np.arange(end - start),
                                    self._num_stages)]
        counts = []
        for s in range(self._num_stages):
            a, b = self.segment_parts[s], self.segment_parts[s + 1]
            counts.append(max(0, min(b, end) - max(a, start)))
        return counts

    def stack_region(self):
        """Maximal run [start, end) of identically-structured Layer entries —
        the region the SPMD schedule shards over the pp axis. Entries outside
        it (embedding, final norm, head, reshapes) run un-pipelined on every
        device (replicated prefix/suffix compute)."""
        sigs = [self._param_signature(e) for e in self.run_function]
        best = (0, 0)
        i = 0
        n = len(sigs)
        while i < n:
            if sigs[i] is None:
                i += 1
                continue
            j = i + 1
            while j < n and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = j
        return best

    def describe(self) -> str:
        lines = []
        for s in range(self._num_stages):
            a, b = self.get_stage_range(s)
            names = [SegmentLayers._class_name(d)
                     for d in self._layers_desc[a:b]]
            lines.append(f"stage {s}: layers [{a}, {b}) = {names}")
        return "\n".join(lines)
