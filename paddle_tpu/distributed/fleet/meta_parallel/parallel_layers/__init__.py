from ...random import RNGStatesTracker, get_rng_state_tracker  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, parallel_matmul, RowParallelLinear,
    VocabParallelEmbedding, shard_constraint,
)
