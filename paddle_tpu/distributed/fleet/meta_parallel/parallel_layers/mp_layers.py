"""Megatron-style tensor-parallel layers, GSPMD-first.

Reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
mp_layers.py (``VocabParallelEmbedding``, ``ColumnParallelLinear``,
``RowParallelLinear``, ``ParallelCrossEntropy``).

The reference materialises per-rank weight SHARDS and calls NCCL around
matmuls. The TPU-native design keeps the LOGICAL full weight on every layer
and attaches a ``PartitionSpec`` (``param.dist_attr``); the jitted train step
places params by that spec and XLA/GSPMD inserts exactly the collectives the
reference hand-codes (identity/allgather enter, allreduce/reduce-scatter
exit). User code is therefore identical to serial code — and parallel==serial
numerics hold by construction. ``split_axis``/``is_distributed`` are kept for
reference API parity (checkpoint tooling reads them).

Degrees come from the active HybridCommunicateGroup; without one the layers
degrade to their serial equivalents.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core.tensor import Tensor, apply_op
from .....nn import functional as F
from .....nn import initializer as I
from .....nn.layer import Layer
from .....nn.param_attr import ParamAttr
from ...base_topology import try_get_hybrid_communicate_group


def _mp_degree_and_axis(mp_group) -> tuple:
    if mp_group is not None:
        return mp_group.nranks, getattr(mp_group, "axis_name", "mp") or "mp"
    hcg = try_get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.get_model_parallel_world_size(), "mp"
    return 1, "mp"


def _active_mesh():
    hcg = try_get_hybrid_communicate_group()
    return hcg.get_mesh() if hcg is not None else None


def shard_constraint(x, spec: P):
    """Annotate an activation's layout (jax.lax.with_sharding_constraint),
    recorded on the autograd tape; no-op without an active mesh or when the
    spec doesn't divide the value."""
    mesh = _active_mesh()
    if mesh is None:
        return x
    sharding = NamedSharding(mesh, spec)
    val = x._value if isinstance(x, Tensor) else x
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        size = 1
        for n in names:
            if n not in mesh.shape:
                return x
            size *= mesh.shape[n]
        if dim >= val.ndim or val.shape[dim] % size != 0:
            return x
    return apply_op("sharding_constraint",
                    lambda v: jax.lax.with_sharding_constraint(v, sharding), x)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp
    (reference: VocabParallelEmbedding — masked local lookup + allreduce;
    here: full logical table with dist_attr P('mp', None))."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self.world_size, self.axis = _mp_degree_and_axis(mp_group)
        if num_embeddings % self.world_size != 0:
            raise ValueError(
                f"vocab size {num_embeddings} not divisible by mp degree "
                f"{self.world_size}")
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else I.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        self.weight.split_axis = 0
        self.weight.dist_attr = P(self.axis, None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return out

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}, mp={self.world_size}"


class ColumnParallelLinear(Layer):
    """Linear with the OUT dim sharded over mp (reference:
    ColumnParallelLinear: y_local = x @ W[:, shard]; gather optional)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = True,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self.world_size, self.axis = _mp_degree_and_axis(mp_group)
        if out_features % self.world_size != 0:
            raise ValueError(
                f"out_features {out_features} not divisible by mp degree "
                f"{self.world_size}")
        self._in_features, self._out_features = in_features, out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else I.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        self.weight.split_axis = 1
        self.weight.dist_attr = P(None, self.axis)
        if has_bias:
            self.bias = self.create_parameter(
                (out_features,), is_bias=True)
            self.bias.is_distributed = self.world_size > 1
            self.bias.split_axis = 0
            self.bias.dist_attr = P(self.axis)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            # leave the out dim sharded: the consumer (RowParallelLinear)
            # wants it parallel — GSPMD keeps the allgather out of the graph
            spec = [None] * (len(out.shape) - 1) + [self.axis]
            out = shard_constraint(out, P(*spec))
        return out

    def extra_repr(self):
        return (f"in={self._in_features}, out={self._out_features}, "
                f"mp={self.world_size}, gather_output={self.gather_output}")


class RowParallelLinear(Layer):
    """Linear with the IN dim sharded over mp (reference: RowParallelLinear:
    y = allreduce(x_local @ W[shard, :]) + b)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = False,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self.world_size, self.axis = _mp_degree_and_axis(mp_group)
        if in_features % self.world_size != 0:
            raise ValueError(
                f"in_features {in_features} not divisible by mp degree "
                f"{self.world_size}")
        self._in_features, self._out_features = in_features, out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else I.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        self.weight.split_axis = 0
        self.weight.dist_attr = P(self.axis, None)
        if has_bias:
            # bias is applied after the (implicit) allreduce: replicated
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.dist_attr = P(None)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            spec = [None] * (len(x.shape) - 1) + [self.axis]
            x = shard_constraint(x, P(*spec))
        out = F.linear(x, self.weight, self.bias)
        spec = [None] * len(out.shape)
        out = shard_constraint(out, P(*spec))
        return out

    def extra_repr(self):
        return (f"in={self._in_features}, out={self._out_features}, "
                f"mp={self.world_size}, input_is_parallel={self.input_is_parallel}")


class ParallelCrossEntropy(Layer):
    """Softmax cross-entropy over vocab-sharded logits (reference:
    ParallelCrossEntropy → c_softmax_with_cross_entropy CUDA op: local max,
    allreduce max, local sum(exp), allreduce sum, masked label pick). Under
    GSPMD the identical collective sequence falls out of the sharded
    logsumexp; numerically this IS softmax CE in fp32."""

    def __init__(self, mp_group=None, name=None, ignore_index: int = -100):
        super().__init__()
        self.world_size, self.axis = _mp_degree_and_axis(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        def ce(logits, lab):
            logits = logits.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=False)
            lab_clipped = jnp.clip(lab, 0, logits.shape[-1] - 1)
            picked = jnp.take_along_axis(
                logits, lab_clipped[..., None], axis=-1)[..., 0]
            loss = lse - picked
            # out-of-range labels that aren't ignore_index surface as NaN
            # (the reference CUDA op errors; under jit, NaN + the NaN
            # checker is the observable equivalent)
            invalid = (lab < 0) | (lab >= logits.shape[-1])
            loss = jnp.where(invalid, jnp.nan, loss)
            mask = (lab != self.ignore_index)
            return jnp.where(mask, loss, 0.0)[..., None]

        return apply_op("parallel_cross_entropy", ce, input, label)
