"""Megatron-style tensor-parallel layers, GSPMD-first.

Reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
mp_layers.py (``VocabParallelEmbedding``, ``ColumnParallelLinear``,
``RowParallelLinear``, ``ParallelCrossEntropy``).

The reference materialises per-rank weight SHARDS and calls NCCL around
matmuls. The TPU-native design keeps the LOGICAL full weight on every layer
and attaches a ``PartitionSpec`` (``param.dist_attr``); the jitted train step
places params by that spec and XLA/GSPMD inserts exactly the collectives the
reference hand-codes (identity/allgather enter, allreduce/reduce-scatter
exit). User code is therefore identical to serial code — and parallel==serial
numerics hold by construction. ``split_axis``/``is_distributed`` are kept for
reference API parity (checkpoint tooling reads them).

Degrees come from the active HybridCommunicateGroup; without one the layers
degrade to their serial equivalents.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core.tensor import Tensor, apply_op
from .....nn import functional as F
from .....nn import initializer as I
from .....nn.layer import Layer
from .....nn.param_attr import ParamAttr
from ...base_topology import try_get_hybrid_communicate_group


def _mp_degree_and_axis(mp_group) -> tuple:
    if mp_group is not None:
        from ....communication.group import resolve_group_axis
        return mp_group.nranks, resolve_group_axis(mp_group, "mp")
    hcg = try_get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.get_model_parallel_world_size(), "mp"
    return 1, "mp"


def _active_mesh():
    hcg = try_get_hybrid_communicate_group()
    return hcg.get_mesh() if hcg is not None else None


def _manual_axis(axis: str) -> bool:
    """True when ``axis`` is a MANUAL axis of the current trace context
    (inside a shard_map manual over it, e.g. the zbh1 engine). GSPMD
    constraints don't apply there — the TP layers switch to explicit
    collectives, the shard_map idiom."""
    cur = jax.sharding.get_abstract_mesh()
    return axis in set(getattr(cur, "manual_axes", ()) or ())


def _mp_copy(x, axis: str):
    """Megatron's ``f``: identity forward, psum backward — marks the point
    where a replicated activation fans out into column-sharded compute, so
    the partial input-grads of the local matmuls sum to the true dx. Only
    meaningful under MANUAL mp (check_vma=False shard_map: no automatic
    transpose collectives)."""

    @jax.custom_vjp
    def f(v):
        return v

    f.defvjp(lambda v: (v, None),
             lambda _, g: (jax.lax.psum(g, axis),))
    return apply_op("mp_copy", f, x)


def _mp_reduce(x, axis: str):
    """Megatron's ``g``: psum forward, identity backward — the row-parallel
    output reduction; the replicated cotangent flows straight to each
    member's partial product."""

    @jax.custom_vjp
    def f(v):
        return jax.lax.psum(v, axis)

    f.defvjp(lambda v: (jax.lax.psum(v, axis), None),
             lambda _, g: (g,))
    return apply_op("mp_reduce", f, x)


def parallel_matmul(x, weight, transpose_y: bool = True,
                    mp_group=None):
    """The tied-head matmul over a vocab-parallel table (reference:
    parallel_matmul in the fleet model zoo: logits = x @ W^T with W
    vocab-sharded, parallel_output=True). GSPMD path: plain matmul, the
    table's dist_attr shards the output. Manual-mp path: f-copy the
    replicated activation first (identity fwd, psum bwd — dx from the
    local-shard contraction is partial), then the local matmul; the
    vocab-sharded logits feed ParallelCrossEntropy."""
    from .....ops import matmul
    world, axis = _mp_degree_and_axis(mp_group)
    if world > 1 and _manual_axis(axis):
        x = _mp_copy(x, axis)
    return matmul(x, weight, transpose_y=transpose_y)


def shard_constraint(x, spec: P):
    """Annotate an activation's layout (jax.lax.with_sharding_constraint),
    recorded on the autograd tape; no-op without an active mesh or when the
    spec doesn't divide the value."""
    mesh = _active_mesh()
    if mesh is None:
        return x
    sharding = NamedSharding(mesh, spec)
    cur = jax.sharding.get_abstract_mesh()
    manual = set(getattr(cur, "manual_axes", ()) or ())
    if manual:
        # inside a (partial-)manual shard_map region (e.g. the zbh1 pp
        # engine): constraints must be built on the trace's abstract mesh,
        # whose axis types mark the manual axes — the stored concrete mesh
        # is all-Auto and jax rejects it. Specs touching a manual axis
        # cannot be constrained from inside; skip those.
        flat = set()
        for entry in spec:
            if entry is None:
                continue
            flat.update(entry if isinstance(entry, tuple) else (entry,))
        if flat & manual:
            return x
        sharding = NamedSharding(cur, spec)
    val = x._value if isinstance(x, Tensor) else x
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        size = 1
        for n in names:
            if n not in mesh.shape:
                return x
            size *= mesh.shape[n]
        if dim >= val.ndim or val.shape[dim] % size != 0:
            return x
    return apply_op("sharding_constraint",
                    lambda v: jax.lax.with_sharding_constraint(v, sharding), x)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp
    (reference: VocabParallelEmbedding — masked local lookup + allreduce;
    here: full logical table with dist_attr P('mp', None))."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self.world_size, self.axis = _mp_degree_and_axis(mp_group)
        if num_embeddings % self.world_size != 0:
            raise ValueError(
                f"vocab size {num_embeddings} not divisible by mp degree "
                f"{self.world_size}")
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else I.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        self.weight.split_axis = 0
        self.weight.dist_attr = P(self.axis, None)

    def forward(self, x):
        if self.world_size > 1 and _manual_axis(self.axis):
            # manual mp: the bound weight is the LOCAL vocab shard —
            # masked local lookup, then the g-reduction (psum fwd,
            # identity bwd: each member's local dW comes from its own
            # shard's rows only)
            def fn(ids, w):
                local_v = w.shape[0]
                r = jax.lax.axis_index(self.axis)
                loc = ids - r * local_v
                valid = (loc >= 0) & (loc < local_v)
                out = jnp.take(w, jnp.clip(loc, 0, local_v - 1), axis=0)
                return out * valid[..., None].astype(out.dtype)

            out = apply_op("vocab_parallel_embedding_manual", fn,
                           x, self.weight)
            return _mp_reduce(out, self.axis)
        out = F.embedding(x, self.weight)
        return out

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}, mp={self.world_size}"


class ColumnParallelLinear(Layer):
    """Linear with the OUT dim sharded over mp (reference:
    ColumnParallelLinear: y_local = x @ W[:, shard]; gather optional)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = True,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self.world_size, self.axis = _mp_degree_and_axis(mp_group)
        if out_features % self.world_size != 0:
            raise ValueError(
                f"out_features {out_features} not divisible by mp degree "
                f"{self.world_size}")
        self._in_features, self._out_features = in_features, out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else I.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        self.weight.split_axis = 1
        self.weight.dist_attr = P(None, self.axis)
        if has_bias:
            self.bias = self.create_parameter(
                (out_features,), is_bias=True)
            self.bias.is_distributed = self.world_size > 1
            self.bias.split_axis = 0
            self.bias.dist_attr = P(self.axis)
        else:
            self.bias = None

    def forward(self, x):
        if self.world_size > 1 and _manual_axis(self.axis):
            # manual mp: weight/bias are LOCAL out-dim shards; the f-copy
            # makes the local matmuls' partial dx sum to the true dx;
            # gather_output all-gathers the out dim
            x = _mp_copy(x, self.axis)
            out = F.linear(x, self.weight, self.bias)
            if self.gather_output:
                out = apply_op(
                    "mp_allgather",
                    lambda v: jax.lax.all_gather(
                        v, self.axis, axis=v.ndim - 1, tiled=True), out)
            return out
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            # leave the out dim sharded: the consumer (RowParallelLinear)
            # wants it parallel — GSPMD keeps the allgather out of the graph
            spec = [None] * (len(out.shape) - 1) + [self.axis]
            out = shard_constraint(out, P(*spec))
        return out

    def extra_repr(self):
        return (f"in={self._in_features}, out={self._out_features}, "
                f"mp={self.world_size}, gather_output={self.gather_output}")


class RowParallelLinear(Layer):
    """Linear with the IN dim sharded over mp (reference: RowParallelLinear:
    y = allreduce(x_local @ W[shard, :]) + b)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = False,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self.world_size, self.axis = _mp_degree_and_axis(mp_group)
        if in_features % self.world_size != 0:
            raise ValueError(
                f"in_features {in_features} not divisible by mp degree "
                f"{self.world_size}")
        self._in_features, self._out_features = in_features, out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else I.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        self.weight.split_axis = 0
        self.weight.dist_attr = P(self.axis, None)
        if has_bias:
            # bias is applied after the (implicit) allreduce: replicated
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.dist_attr = P(None)
        else:
            self.bias = None

    def forward(self, x):
        if self.world_size > 1 and _manual_axis(self.axis):
            # manual mp: local partial product, g-reduction (psum fwd,
            # identity bwd), then the replicated bias exactly once. A
            # replicated (non-parallel) input is sliced to this member's
            # in-dim shard first — the GSPMD path's split constraint,
            # done explicitly.
            if not self.input_is_parallel:
                def split_in(v):
                    local_in = self._in_features // self.world_size
                    r = jax.lax.axis_index(self.axis)
                    return jax.lax.dynamic_slice_in_dim(
                        v, r * local_in, local_in, axis=v.ndim - 1)
                x = apply_op("mp_split_in", split_in, x)
            out = F.linear(x, self.weight)
            out = _mp_reduce(out, self.axis)
            if self.bias is not None:
                out = out + self.bias
            return out
        if self.input_is_parallel:
            spec = [None] * (len(x.shape) - 1) + [self.axis]
            x = shard_constraint(x, P(*spec))
        out = F.linear(x, self.weight, self.bias)
        spec = [None] * len(out.shape)
        out = shard_constraint(out, P(*spec))
        return out

    def extra_repr(self):
        return (f"in={self._in_features}, out={self._out_features}, "
                f"mp={self.world_size}, input_is_parallel={self.input_is_parallel}")


class ParallelCrossEntropy(Layer):
    """Softmax cross-entropy over vocab-sharded logits (reference:
    ParallelCrossEntropy → c_softmax_with_cross_entropy CUDA op: local max,
    allreduce max, local sum(exp), allreduce sum, masked label pick). Under
    GSPMD the identical collective sequence falls out of the sharded
    logsumexp; numerically this IS softmax CE in fp32."""

    def __init__(self, mp_group=None, name=None, ignore_index: int = -100):
        super().__init__()
        self.world_size, self.axis = _mp_degree_and_axis(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        if self.world_size > 1 and _manual_axis(self.axis):
            return self._forward_manual(input, label)

        def ce(logits, lab):
            logits = logits.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=False)
            lab_clipped = jnp.clip(lab, 0, logits.shape[-1] - 1)
            picked = jnp.take_along_axis(
                logits, lab_clipped[..., None], axis=-1)[..., 0]
            loss = lse - picked
            # out-of-range labels that aren't ignore_index surface as NaN
            # (the reference CUDA op errors; under jit, NaN + the NaN
            # checker is the observable equivalent)
            invalid = (lab < 0) | (lab >= logits.shape[-1])
            loss = jnp.where(invalid, jnp.nan, loss)
            mask = (lab != self.ignore_index)
            return jnp.where(mask, loss, 0.0)[..., None]

        return apply_op("parallel_cross_entropy", ce, input, label)

    def _forward_manual(self, input, label):
        """Manual mp: the reference's c_softmax_with_cross_entropy,
        explicitly — local max / pmax, shifted local sum(exp) / psum,
        masked local label pick / psum. The backward is the analytic
        (softmax_local - onehot_local) * ct, a custom_vjp: the builtin
        collective transposes (psum^T = psum) would double-count under
        the engine's local-grad check_vma=False contract."""
        axis = self.axis
        ignore = self.ignore_index
        world = self.world_size

        def stats(logits, lab):
            local_v = logits.shape[-1]
            off = jax.lax.axis_index(axis) * local_v
            m = jax.lax.pmax(jnp.max(logits, axis=-1), axis)     # global max
            sumexp = jax.lax.psum(
                jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), axis)
            lse = m + jnp.log(sumexp)
            loc = lab - off
            mine = (loc >= 0) & (loc < local_v)
            loc_c = jnp.clip(loc, 0, local_v - 1)
            return lse, loc_c, mine

        def loss_of(logits, lab, lse, loc_c, mine):
            picked_local = jnp.take_along_axis(
                logits, loc_c[..., None], axis=-1)[..., 0]
            picked = jax.lax.psum(
                jnp.where(mine, picked_local, 0.0), axis)
            loss = lse - picked
            invalid = (lab < 0) | (lab >= logits.shape[-1] * world)
            loss = jnp.where(invalid, jnp.nan, loss)
            return jnp.where(lab != ignore, loss, 0.0)[..., None]

        @jax.custom_vjp
        def ce(logits, lab):
            logits = logits.astype(jnp.float32)
            lse, loc_c, mine = stats(logits, lab)
            return loss_of(logits, lab, lse, loc_c, mine)

        def ce_fwd(logits, lab):
            logits = logits.astype(jnp.float32)
            lse, loc_c, mine = stats(logits, lab)
            return (loss_of(logits, lab, lse, loc_c, mine),
                    (logits, lab, lse, loc_c, mine))

        def ce_bwd(res, g):
            logits, lab, lse, loc_c, mine = res
            softmax = jnp.exp(logits - lse[..., None])
            onehot = (jax.nn.one_hot(loc_c, logits.shape[-1],
                                     dtype=logits.dtype)
                      * mine[..., None].astype(logits.dtype))
            active = ((lab != ignore) & (lab >= 0)
                      & (lab < logits.shape[-1] * world))
            ct = g[..., 0] * active.astype(logits.dtype)
            return ((softmax - onehot) * ct[..., None], None)

        ce.defvjp(ce_fwd, ce_bwd)
        return apply_op("parallel_cross_entropy_manual", ce, input, label)
