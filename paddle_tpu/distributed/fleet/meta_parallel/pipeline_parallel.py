"""Pipeline-parallel execution: ``PipelineParallel`` + ``PipelineTrainStep``.

Reference: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
(``PipelineParallel.train_batch`` → ``_forward_backward_pipeline``, 1F1B) and
.../pp_utils/p2p_communication.py (NCCL send/recv with shape-meta handshake).

TPU-native design — the "collective pipelining" construct (GSPMD-style)
instead of multi-process p2p:

  * The PipelineLayer's uniform block region is STACKED: every leaf gets a
    leading ``(S, L, ...)`` axis (S = pp stages, L = blocks per stage),
    sharded ``P('pp', ...)`` over the mesh. Each device holds exactly its
    stage's weights — same memory footprint as the reference's per-rank
    stage build.
  * One jitted program runs ``M + S - 1`` ticks in a ``lax.scan``. Each tick
    vmaps the stage body over the stage axis (GSPMD partitions it across the
    pp devices) and shifts the activation buffer one stage forward with
    ``jnp.roll`` along the stage axis — XLA lowers that to a
    ``collective-permute`` over ICI, the TPU analogue of send_v2/recv_v2.
    Stage 0 feeds microbatch ``t``; the last stage emits microbatch
    ``t - (S-1)``.
  * Backward is jax autodiff through the scan: the transpose of the shift is
    the reverse-direction permute and the scan transposes to a reverse-time
    scan — the backward pipeline falls out of the forward schedule.
  * Schedules: the reference's FThenB and 1F1B differ only in peak activation
    memory (bubble fraction is (S-1)/(M+S-1) for both). Under XLA autodiff
    the equivalent memory control is ``jax.checkpoint`` on the per-block
    body (saves only stage inputs, recomputes inside backward) — so
    ``schedule="1F1B"`` maps to remat=True and ``"FThenB"`` to remat=False.
  * Embedding / final-norm / head (the non-uniform prefix/suffix) run
    outside the pipelined region, replicated over pp (sharded over dp/mp as
    annotated). Tied embeddings (SharedLayerDesc) hold ONE parameter; grads
    from both uses sum naturally under autodiff.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ....core import autograd
from ....core.tensor import Tensor
from ....jit import tree_to_tensors, tree_to_values
from ....nn.layer import Layer
from ....optimizer.lr import LRScheduler
from .pp_layers import PipelineLayer, _SharedCall
from .sharding.group_sharded_utils import (
    extend_spec_with_sharding, resolve_sharding_axis,
)

_STACK_PREFIX = "@stacked."


@contextlib.contextmanager
def _bind_params(layer: Layer, rel2val: Dict[str, Any]):
    """Temporarily substitute a layer's parameter values (the per-entry core
    of jit.functional_call, reused here because pipeline entries are bound
    one at a time while tracing)."""
    named = dict(layer.named_parameters())
    saved = []
    try:
        for rel, v in rel2val.items():
            t = named[rel]
            saved.append((t, t._value))
            t._value = v
        yield
    finally:
        for t, v in saved:
            t._value = v


def make_stage_fn(template: Layer, block_rels: List[str], remat: bool,
                  masked: bool = False):
    """The per-stage compute shared by every schedule: scan the stage's L
    stacked blocks through the template layer, functionally bound.
    stage_params: tuple of (L, ...) leaves ordered like block_rels.

    ``masked=True`` (uneven ``seg_method`` splits, VERDICT r4 item 4):
    ``stage_fn(stage_params, count, x)`` — stages are padded to the
    maximum block count and slot ``l`` passes the activation through
    unchanged when ``l >= count``, so every stage runs the same SPMD
    program while executing only its segment's blocks. Padding slots
    burn (Lmax - count)/Lmax of the stage's FLOPs — the price of
    uniformity; the even split costs nothing extra."""

    def block_apply(lparams, x):
        rel2val = dict(zip(block_rels, lparams))
        with _bind_params(template, rel2val), autograd.functional_guard():
            out = template(Tensor(x, stop_gradient=True))
        return tree_to_values(out)

    if remat:
        block_apply = jax.checkpoint(block_apply)

    if masked:
        def stage_fn(stage_params, count, x):
            L = stage_params[0].shape[0]

            def body(carry, inp):
                l, lp = inp
                y = block_apply(lp, carry)
                return jnp.where(l < count, y, carry), None

            y, _ = jax.lax.scan(
                body, x, (jnp.arange(L, dtype=jnp.int32),
                          tuple(stage_params)))
            return y
    else:
        def stage_fn(stage_params, x):
            def body(carry, lp):
                return block_apply(lp, carry), None

            y, _ = jax.lax.scan(body, x, stage_params)
            return y

    return stage_fn


def _mesh_filter_spec(spec: Optional[P], mesh: Mesh) -> P:
    """Drop axes absent from this mesh from a declared PartitionSpec."""
    if spec is None:
        return P()
    entries = []
    for e in spec:
        if e is None:
            entries.append(None)
            continue
        names = tuple(n for n in ((e,) if isinstance(e, str) else e)
                      if n in mesh.axis_names and mesh.shape[n] >= 1)
        entries.append(names[0] if len(names) == 1 else (names or None))
    return P(*entries)


class PipelineTrainStep:
    """One jitted fwd+bwd+update over the SPMD pipeline schedule.

    Parameter layout (the flat dict the optimizer sees):
      - ``"{idx}.{rel}"``     — prefix/suffix entry params (idx = position in
                                 PipelineLayer.run_function)
      - ``"@stacked.{rel}"``  — block params stacked to (S, L, *shape),
                                 sharded P('pp', None, *declared_spec)
    """

    def __init__(self, pipe_layer: PipelineLayer, optimizer,
                 mesh: Mesh, num_microbatches: int,
                 loss_fn: Optional[Callable] = None,
                 remat: bool = True, donate: bool = True,
                 sharding_level: Optional[int] = None,
                 sharding_axis: Optional[str] = None,
                 virtual_pp_degree: int = 1,
                 abstract: bool = False, param_dtype=None,
                 lowering_platform: str = "tpu",
                 schedule: str = "auto"):
        """``abstract=True`` builds the FULL sharded program over
        ``jax.ShapeDtypeStruct`` parameters (no arrays are ever
        materialized or placed): ``mesh`` may then be a
        ``jax.sharding.AbstractMesh`` of any size — e.g. a simulated
        v5p-128 — and ``lower()`` produces the StableHLO for
        ``lowering_platform``. ``param_dtype`` overrides the parameter
        dtype (bf16 params + f32 master weights is the TPU recipe)
        without touching data. Abstract steps cannot run — only lower."""
        if "pp" not in mesh.shape:
            raise ValueError("mesh has no 'pp' axis")
        self.pipe_layer = pipe_layer
        self.optimizer = optimizer
        self.mesh = mesh
        self._abstract = bool(abstract)
        self._lowering_platform = lowering_platform
        donate = donate and not abstract
        if param_dtype is not None and not abstract:
            raise ValueError(
                "param_dtype is only applied in abstract mode; for a live "
                "step cast the model first (model.to(dtype=...))")
        if schedule not in ("auto", "zbh1"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                             "'auto' (lockstep FThenB/remat/VPP) or 'zbh1'")
        self._schedule = schedule
        if schedule == "zbh1":
            # v1 scope of the zero-bubble engine (pipeline_zbh1.py)
            if abstract:
                raise NotImplementedError(
                    "zbh1 + abstract lowering: the zbh1 builder does not "
                    "pin abstract in_shardings yet; lower the lockstep "
                    "schedule instead")
            if virtual_pp_degree != 1:
                raise NotImplementedError("zbh1 + interleaved VPP")
            eff_level = (sharding_level
                         or getattr(optimizer, "_group_sharded_level", 0)
                         or getattr(pipe_layer, "_group_sharded_level", 0)
                         or 0)
            if eff_level and int(eff_level) >= 3:
                raise NotImplementedError(
                    "zbh1 + ZeRO stage 3: dp-sharded PARAMS would be "
                    "all-gathered at shard_map entry with no GSPMD "
                    "control over placement; levels 1/2 compose (the "
                    "optimizer update and grad resharding run outside "
                    "the manual region), or use schedule='auto'")
        self.S = mesh.shape["pp"]
        self.M = int(num_microbatches)
        self.V = int(virtual_pp_degree)
        if self.M < self.S:
            raise ValueError(
                f"accumulate_steps ({self.M}) must be >= pp degree ({self.S}) "
                "or the pipeline is mostly bubble")
        if self.V < 1:
            raise ValueError(f"virtual_pp_degree must be >= 1, got {self.V}")
        if self.V > 1 and self.M % self.S != 0:
            # interleaved schedule circulates microbatch groups of S around
            # the ring V times; ragged groups would leave permanent holes
            raise ValueError(
                f"interleaved schedule needs accumulate_steps ({self.M}) "
                f"divisible by pp degree ({self.S})")
        self.loss_fn = loss_fn or pipe_layer._loss_fn
        if self.loss_fn is None:
            raise ValueError("PipelineLayer needs a loss_fn for train_batch")

        start, end = pipe_layer.stack_region()
        n_blocks = end - start
        if n_blocks < self.S * self.V:
            raise ValueError(
                f"stackable block region has {n_blocks} layers < "
                f"{self.S} stages x {self.V} virtual chunks")
        # stage split: seg_method's boundaries are honoured (VERDICT r4
        # item 4). Even counts run the exact stacked scan; uneven counts
        # run the padded masked scan (V == 1, schedule 'auto' only).
        counts = pipe_layer.stage_block_counts() if self.V == 1 else None
        if counts is not None and len(set(counts)) > 1:
            if schedule == "zbh1":
                raise NotImplementedError(
                    f"zbh1 needs an even stage split; seg_method yields "
                    f"per-stage block counts {counts} — use "
                    f"schedule='auto' (padded masked scan) or an even "
                    f"seg_method")
            self.L = max(counts)
            self._stage_counts = np.asarray(counts, np.int32)
            bounds = np.concatenate([[start], start + np.cumsum(counts)])
            self._stage_slots = [list(range(bounds[s], bounds[s + 1]))
                                 for s in range(self.S)]
        else:
            # even split (exact; no padding). Blocks must divide evenly;
            # leftovers join the suffix (replicated — correct, slightly
            # wasteful, and only happens for unusual layer counts)
            self.L = n_blocks // (self.S * self.V)
            end = start + self.L * self.S * self.V
            self._stage_counts = None
            self._stage_slots = None
        self._start, self._end = start, end
        self.template: Layer = pipe_layer.run_function[start]
        rf = pipe_layer.run_function
        self._prefix = [(i, rf[i]) for i in range(0, start)]
        self._suffix = [(i, rf[i]) for i in range(end, len(rf))]

        # owner run_function index for each shared key (param lives there) —
        # recorded at build time, covering owners whose own entry is a
        # _SharedCall (forward_func on the first occurrence)
        self._shared_owner: Dict[str, int] = dict(pipe_layer._shared_owner_idx)

        # ---- flat params + shardings -------------------------------------
        params: Dict[str, Any] = {}
        specs: Dict[str, P] = {}
        named_for_masks: Dict[str, Any] = {}  # key -> Parameter (wd masks)

        def add_layer_params(idx, layer):
            for rel, p in layer.named_parameters():
                params[f"{idx}.{rel}"] = p._value
                named_for_masks[f"{idx}.{rel}"] = p
                specs[f"{idx}.{rel}"] = _mesh_filter_spec(
                    getattr(p, "dist_attr", None), mesh)

        def add_entry_params(idx, entry):
            if isinstance(entry, _SharedCall) or not isinstance(entry, Layer):
                return
            add_layer_params(idx, entry)

        for idx, e in self._prefix:
            add_entry_params(idx, e)
        for idx, e in self._suffix:
            add_entry_params(idx, e)
        # shared layers' params always live at their owner index, even when
        # every occurrence (incl. the owning one) is a _SharedCall
        for key, idx in self._shared_owner.items():
            add_layer_params(idx, pipe_layer.shared_layers[key])

        self._block_rels = [rel for rel, _ in self.template.named_parameters()]
        tmpl_params = dict(self.template.named_parameters())
        block_params = [dict(rf[j].named_parameters())
                        for j in range(start, end)]

        def _pdt(dtype):
            return jnp.dtype(param_dtype) if param_dtype else jnp.dtype(dtype)

        for rel in self._block_rels:
            base = _mesh_filter_spec(
                getattr(tmpl_params[rel], "dist_attr", None), mesh)
            leaf_shape = tuple(tmpl_params[rel].shape)
            if self.V == 1:
                shp = (self.S, self.L) + leaf_shape
                specs[_STACK_PREFIX + rel] = P("pp", None, *base)
            else:
                # interleaved: depth chunk c = v*S + s lives on device s as
                # virtual chunk v (Megatron VPP assignment: device s holds
                # chunks {s, s+S, ...}) -> layout (S, V, L, *shape)
                shp = (self.S, self.V, self.L) + leaf_shape
                specs[_STACK_PREFIX + rel] = P("pp", None, None, *base)
            if abstract:
                stacked = jax.ShapeDtypeStruct(
                    shp, _pdt(tmpl_params[rel]._value.dtype))
            elif self._stage_counts is not None:
                # uneven seg_method split: stage rows padded to Lmax with
                # template values (masked out by the stage scan)
                tmpl_val = tmpl_params[rel]._value
                rows = []
                for s in range(self.S):
                    vals = [block_params[j - start][rel]._value
                            for j in self._stage_slots[s]]
                    vals += [tmpl_val] * (self.L - len(vals))
                    rows.append(jnp.stack(vals))
                stacked = jnp.stack(rows)
            else:
                leaves = [bp[rel]._value for bp in block_params]
                if self.V == 1:
                    stacked = jnp.stack(leaves).reshape(shp)
                else:
                    stacked = jnp.stack(leaves).reshape(
                        (self.V, self.S) + shp[2:])
                    stacked = jnp.swapaxes(stacked, 0, 1)
            params[_STACK_PREFIX + rel] = stacked
            # one wd scalar covers the whole stacked array, so the decay
            # decision must be uniform across the stacked layers; the
            # uniformity is CHECKED below in _check_stack_decay_uniform
            # (a per-layer-divergent callback would otherwise be applied
            # template-wide silently)
            named_for_masks[_STACK_PREFIX + rel] = tmpl_params[rel]
        self._stack_mask_params = {
            _STACK_PREFIX + rel: [bp[rel] for bp in block_params]
            for rel in self._block_rels}

        # ---- ZeRO composition (same resolution as hapi.TrainStep) --------
        level = sharding_level
        if level is None:
            level = max(getattr(optimizer, "_group_sharded_level", 0),
                        getattr(pipe_layer, "_group_sharded_level", 0))
        axis = (sharding_axis
                or getattr(optimizer, "_sharding_axis", None)
                or getattr(pipe_layer, "_sharding_axis", None))
        if level and (axis is None or axis not in mesh.shape
                      or mesh.shape[axis] <= 1):
            axis = resolve_sharding_axis(mesh)
        if axis is None:
            level = 0
        self.sharding_level, self.sharding_axis = level, axis
        if schedule == "zbh1" and level and axis != "dp":
            # the zero-bubble engine composes ZeRO only over the dp axis
            # (the manual data axis its pmean runs on); fail here, not at
            # first-step trace with an opaque mesh-axis error
            raise NotImplementedError(
                f"zbh1 + ZeRO over axis {axis!r}: the zero-bubble engine "
                "shards optimizer state over 'dp' only — use a dp axis "
                "for sharding or schedule='auto'")

        if level >= 3:
            specs = {k: extend_spec_with_sharding(
                s, params[k].shape, mesh, axis) for k, s in specs.items()}
        self.param_shardings = {
            k: NamedSharding(mesh, s) for k, s in specs.items()}
        if level >= 1:
            self.opt_shardings = {
                k: NamedSharding(mesh, extend_spec_with_sharding(
                    specs[k], params[k].shape, mesh, axis)) for k in params}
        else:
            self.opt_shardings = dict(self.param_shardings)

        if schedule == "zbh1":
            # the manual engine uses exactly pp, dp, and the axes named
            # by param specs (TP); any OTHER size>1 axis (sep, sharding)
            # would silently replicate all work — the user configured a
            # parallelism the engine would not deliver. Fail loudly.
            named = set()
            for s in specs.values():
                for entry in s:
                    if entry is None:
                        continue
                    named.update(entry if isinstance(entry, tuple)
                                 else (entry,))
            for ax, size in mesh.shape.items():
                if size > 1 and ax not in {"pp", "dp"} | named:
                    raise NotImplementedError(
                        f"zbh1: mesh axis {ax!r} (size {size}) is neither "
                        "pp/dp nor named by any param spec — the manual "
                        "engine would replicate its work, not parallelize "
                        "it; use schedule='auto' or drop the axis")

        if abstract:
            # re-struct every leaf so param_dtype applies uniformly (lazy
            # meta params arrive as f32 ShapeDtypeStructs)
            params = {k: jax.ShapeDtypeStruct(tuple(v.shape), _pdt(v.dtype))
                      for k, v in params.items()}
        else:
            params = {k: jax.device_put(v, self.param_shardings[k])
                      for k, v in params.items()}
        self.params = params
        if hasattr(optimizer, "resolve_decay_masks"):
            optimizer.resolve_decay_masks(named_for_masks)
            self._check_stack_decay_uniform(optimizer)
        if abstract:
            self.opt_state = jax.eval_shape(optimizer.init_state_tree, params)
        else:
            self.opt_state = optimizer.init_state_tree(params)
            self.opt_state["slots"] = {
                k: jax.tree.map(
                    lambda s, _k=k: jax.device_put(s, self.opt_shardings[_k]),
                    slot)
                for k, slot in self.opt_state["slots"].items()}
            if self.opt_state.get("master"):
                self.opt_state["master"] = {
                    k: jax.device_put(v, self.opt_shardings[k])
                    for k, v in self.opt_state["master"].items()}

        # data + activation shardings
        data_axes = tuple(a for a in ("dp", "sharding")
                          if a in mesh.shape and mesh.shape[a] > 1)
        self._data_sharding = NamedSharding(
            mesh, P(data_axes if data_axes else None))
        self._act_sharding = NamedSharding(
            mesh, P("pp", data_axes if data_axes else None))

        if self._schedule == "zbh1":
            self._build_zbh1_step(optimizer, remat, donate)
            return

        # ---- the jitted step ---------------------------------------------
        template = self.template
        S, L, M, V = self.S, self.L, self.M, self.V
        loss_fn = self.loss_fn
        act_spec = self._act_sharding
        run_entries = self._run_entries

        masked = self._stage_counts is not None
        stage_fn = make_stage_fn(template, self._block_rels, remat,
                                 masked=masked)
        counts_arr = (jnp.asarray(self._stage_counts) if masked else None)

        def pipeline_plain(stacked, h):
            # h: (M, mb, ...) microbatch activations entering stage 0
            stage_params = tuple(stacked[_STACK_PREFIX + rel]
                                 for rel in self._block_rels)
            pad = jnp.zeros((S - 1,) + h.shape[1:], h.dtype)
            feed = jnp.concatenate([h, pad], axis=0)
            buf = jnp.zeros((S,) + h.shape[1:], h.dtype)
            buf = jax.lax.with_sharding_constraint(buf, act_spec)

            def tick(buf, x_t):
                buf = jax.lax.dynamic_update_index_in_dim(buf, x_t, 0, 0)
                if masked:
                    out = jax.vmap(stage_fn)(stage_params, counts_arr, buf)
                else:
                    out = jax.vmap(stage_fn)(stage_params, buf)
                out = jax.lax.with_sharding_constraint(out, act_spec)
                y_t = out[-1]
                # stage i -> i+1; on the pp-sharded stage axis XLA lowers
                # this roll to a collective-permute over ICI
                nxt = jnp.roll(out, 1, axis=0)
                nxt = jax.lax.with_sharding_constraint(nxt, act_spec)
                return nxt, y_t

            _, ys = jax.lax.scan(tick, buf, feed)
            return ys[S - 1:]          # (M, mb, ...) in microbatch order

        def stage_fn_v(stage_chunks, v, x):
            # stage_chunks: tuple of (V, L, ...) leaves for this stage;
            # select the active virtual chunk by (traced) phase index v
            chunk = tuple(
                jax.lax.dynamic_index_in_dim(a, v, 0, keepdims=False)
                for a in stage_chunks)
            return stage_fn(chunk, x)

        def pipeline_interleaved(stacked, h):
            """Interleaved (VPP) schedule, reference 'virtual pipeline' /
            interleaved 1F1B (Megatron fig. 4; reference pass:
            pipeline_scheduler_pass VPP mode). Microbatch groups of S
            circulate the S-device ring V times; each tick every device
            applies ONE chunk of L blocks (1/V of its layers), so the
            fill/drain bubble is (S-1) ticks of L blocks instead of (S-1)
            ticks of V*L blocks: bubble fraction (S-1)/(M*V + S - 1)."""
            stage_params = tuple(stacked[_STACK_PREFIX + rel]
                                 for rel in self._block_rels)
            T = M * V + S - 1
            feed_idx = np.zeros((T,), np.int32)
            feed_mask = np.zeros((T,), bool)
            phases = np.zeros((T, S), np.int32)
            coll_idx = np.zeros((T,), np.int32)
            coll_mask = np.zeros((T,), bool)
            for t in range(T):
                g, r = divmod(t, V * S)
                if r < S and g * S + r < M:
                    feed_mask[t] = True
                    feed_idx[t] = g * S + r
                for s in range(S):
                    phases[t, s] = ((t - s) // S) % V if t >= s else 0
            for g in range(M // S):
                for i in range(S):
                    t = g * V * S + (V - 1) * S + i + (S - 1)
                    coll_mask[t] = True
                    coll_idx[t] = g * S + i
            buf = jnp.zeros((S,) + h.shape[1:], h.dtype)
            buf = jax.lax.with_sharding_constraint(buf, act_spec)
            acc = jnp.zeros((M,) + h.shape[1:], h.dtype)

            def tick(carry, xs):
                buf, acc = carry
                fi, fm, vs, ci, cm = xs
                x_t = jax.lax.dynamic_index_in_dim(h, fi, 0, keepdims=False)
                slot0 = jnp.where(fm, x_t, buf[0])
                buf = jax.lax.dynamic_update_index_in_dim(buf, slot0, 0, 0)
                out = jax.vmap(stage_fn_v)(stage_params, vs, buf)
                out = jax.lax.with_sharding_constraint(out, act_spec)
                y_t = out[-1]
                prev = jax.lax.dynamic_index_in_dim(acc, ci, 0, keepdims=False)
                acc = jax.lax.dynamic_update_index_in_dim(
                    acc, jnp.where(cm, y_t, prev), ci, 0)
                # ring shift incl. wrap S-1 -> 0 (chunk v done on the last
                # device continues as chunk v+1 on device 0)
                nxt = jnp.roll(out, 1, axis=0)
                nxt = jax.lax.with_sharding_constraint(nxt, act_spec)
                return (nxt, acc), None

            (_, acc), _ = jax.lax.scan(
                tick, (buf, acc),
                (jnp.asarray(feed_idx), jnp.asarray(feed_mask),
                 jnp.asarray(phases), jnp.asarray(coll_idx),
                 jnp.asarray(coll_mask)))
            return acc                 # (M, mb, ...) in microbatch order

        pipeline = pipeline_plain if V == 1 else pipeline_interleaved

        def loss_of(params, inputs, labels):
            # prefix on the full flattened batch (standard 3D shapes), then
            # pipeline over microbatches, then suffix + loss on the full batch
            x = run_entries(self._prefix, params, inputs)
            x = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            y = pipeline(params, x)
            y = y.reshape((y.shape[0] * y.shape[1],) + y.shape[2:])
            out = run_entries(self._suffix, params, y)
            with autograd.functional_guard():
                loss = loss_fn(*tree_to_tensors((out, labels)))
            return tree_to_values(loss)

        def step(params, opt_state, lr, inputs, labels):
            loss, grads = jax.value_and_grad(loss_of)(params, inputs, labels)
            if self.sharding_level >= 2:
                grads = {k: jax.lax.with_sharding_constraint(
                    g, self.opt_shardings[k]) for k, g in grads.items()}
            new_params, new_state = optimizer.functional_update(
                params, grads, opt_state, lr)
            new_params = {k: jax.lax.with_sharding_constraint(
                v, self.param_shardings[k]) for k, v in new_params.items()}
            new_state["slots"] = {
                k: jax.tree.map(
                    lambda s, _k=k: jax.lax.with_sharding_constraint(
                        s, self.opt_shardings[_k]), slot)
                for k, slot in new_state["slots"].items()}
            if new_state.get("master"):
                new_state["master"] = {
                    k: jax.lax.with_sharding_constraint(
                        v, self.opt_shardings[k])
                    for k, v in new_state["master"].items()}
            return loss, new_params, new_state

        if abstract:
            # ShapeDtypeStruct args carry no placement — pin every input's
            # sharding explicitly so the lowering is the real SPMD program
            rep = NamedSharding(mesh, P())
            opt_sh_tree = {
                "slots": {
                    k: jax.tree.map(lambda _, s=self.opt_shardings[k]: s,
                                    slot)
                    for k, slot in self.opt_state["slots"].items()},
                "t": rep,
                "master": (
                    {k: self.opt_shardings[k]
                     for k in self.opt_state["master"]}
                    if self.opt_state.get("master") is not None else None),
            }
            self._jit_step = jax.jit(
                step,
                in_shardings=(self.param_shardings, opt_sh_tree, rep,
                              self._data_sharding, self._data_sharding))
        else:
            self._jit_step = jax.jit(
                step, donate_argnums=(0, 1) if donate else ())
        self._step_count = 0

    # ---------------------------------------------------- zbh1 (zero bubble)
    def _build_zbh1_step(self, optimizer, remat, donate):
        from .pipeline_zbh1 import build_zbh1_loss_and_grads

        S, M = self.S, self.M
        mesh = self.mesh
        run_entries = self._run_entries
        loss_fn = self.loss_fn
        block_rels = self._block_rels
        template = self.template
        prefix_entries, suffix_entries = self._prefix, self._suffix

        # tied/shared layers: their params live at the OWNER index; both
        # phases read them, so they ride as a third replicated group with
        # cross-phase gradient routing inside the zbh1 kernel
        shared_keys = [
            f"{self._shared_owner[key]}.{rel}"
            for key, layer in self.pipe_layer.shared_layers.items()
            for rel, _ in layer.named_parameters()]

        def entry_keys(entries):
            return [f"{idx}.{rel}" for idx, e in entries
                    if isinstance(e, Layer)
                    for rel, _ in e.named_parameters()
                    if f"{idx}.{rel}" not in shared_keys]

        prefix_keys = entry_keys(prefix_entries)
        suffix_keys = entry_keys(suffix_entries)

        def prefix_apply(prefix_params, shared_params, ids_mb):
            return run_entries(prefix_entries,
                               {**prefix_params, **shared_params}, ids_mb)

        def suffix_loss(suffix_params, shared_params, y_mb, labels_mb):
            out = run_entries(suffix_entries,
                              {**suffix_params, **shared_params}, y_mb)
            with autograd.functional_guard():
                loss = loss_fn(*tree_to_tensors((out, labels_mb)))
            return tree_to_values(loss)

        dp_axis = "dp" if ("dp" in mesh.shape
                           and mesh.shape["dp"] > 1) else None
        dp_size = mesh.shape.get("dp", 1) if dp_axis else 1

        def step(params, opt_state, lr, inputs, labels):
            x = inputs.reshape((M, inputs.shape[0] // M) + inputs.shape[1:])
            lab = labels.reshape(
                (M, labels.shape[0] // M) + labels.shape[1:])
            if x.shape[1] % dp_size:
                raise ValueError(
                    f"microbatch size {x.shape[1]} not divisible by dp "
                    f"degree {dp_size}")
            pre = {k: params[k] for k in prefix_keys}
            suf = {k: params[k] for k in suffix_keys}
            shr = {k: params[k] for k in shared_keys}
            stacked = tuple(params[_STACK_PREFIX + rel]
                            for rel in block_rels)
            # act shape is per-dp-shard inside the manual region
            local_in = (x.shape[1] // dp_size,) + x.shape[2:]
            act_sds = jax.eval_shape(
                prefix_apply, pre, shr,
                jax.ShapeDtypeStruct(local_in, x.dtype))
            zfn = build_zbh1_loss_and_grads(
                mesh, S, M, block_rels, template,
                prefix_apply, suffix_loss, act_sds, remat=remat,
                dp_axis=dp_axis,
                stacked_specs=[
                    self.param_shardings[_STACK_PREFIX + rel].spec
                    for rel in block_rels],
                pre_specs={k: self.param_shardings[k].spec
                           for k in prefix_keys},
                suf_specs={k: self.param_shardings[k].spec
                           for k in suffix_keys},
                shr_specs={k: self.param_shardings[k].spec
                           for k in shared_keys})
            loss, dWt, dPre, dSuf, dShr = zfn(stacked, pre, suf, shr,
                                              x, lab)
            grads = {_STACK_PREFIX + rel: dWt[i]
                     for i, rel in enumerate(block_rels)}
            grads.update(dPre)
            grads.update(dSuf)
            grads.update(dShr)
            if self.sharding_level and self.sharding_level >= 2:
                # ZeRO-2: grads live dp-sharded from here on (the reshard
                # happens OUTSIDE the manual region, like the auto path)
                grads = {k: jax.lax.with_sharding_constraint(
                    g, self.opt_shardings[k]) for k, g in grads.items()}
            new_params, new_state = optimizer.functional_update(
                params, grads, opt_state, lr)
            # keep output layouts identical to inputs (donation + steady
            # state), exactly like the lockstep step: params AND slots
            new_params = {k: jax.lax.with_sharding_constraint(
                v, self.param_shardings[k]) for k, v in new_params.items()}
            new_state["slots"] = {
                k: jax.tree.map(
                    lambda s, _k=k: jax.lax.with_sharding_constraint(
                        s, self.opt_shardings[_k]), slot)
                for k, slot in new_state["slots"].items()}
            if new_state.get("master"):
                new_state["master"] = {
                    k: jax.lax.with_sharding_constraint(
                        v, self.opt_shardings[k])
                    for k, v in new_state["master"].items()}
            return loss, new_params, new_state

        self._jit_step = jax.jit(
            step, donate_argnums=(0, 1) if donate else ())
        self._step_count = 0

    # ------------------------------------------------------- abstract mode
    def lower(self, inputs: jax.ShapeDtypeStruct,
              labels: jax.ShapeDtypeStruct):
        """Trace + lower the full sharded train step for the target
        platform (abstract mode). Works from any host — no devices of the
        target platform are needed."""
        lr = jax.ShapeDtypeStruct((), jnp.float32)
        traced = self._jit_step.trace(self.params, self.opt_state, lr,
                                      inputs, labels)
        return traced.lower(
            lowering_platforms=(self._lowering_platform,))

    def per_device_state_bytes(self) -> Dict[str, int]:
        """Analytic per-device bytes of the resident training state
        (params + optimizer slots + master weights), from the sharding
        table — the HBM-fit check for a target topology. Accounting
        (per-dim CEIL division, so non-divisible dims that pad up on
        device never undercount) lives in the shared memwatch helper —
        one code path with ``tools/memory_70b.py``."""
        from ....observability.memory import sharded_param_bytes

        def shard_bytes(sds, sharding):
            return sharded_param_bytes(sds.shape, sds.dtype,
                                       sharding.spec, self.mesh.shape)

        out = {"params": 0, "slots": 0, "master": 0}
        for k, v in self.params.items():
            out["params"] += shard_bytes(v, self.param_shardings[k])
        for k, slot in self.opt_state["slots"].items():
            for leaf in jax.tree.leaves(slot):
                out["slots"] += shard_bytes(leaf, self.opt_shardings[k])
        if self.opt_state.get("master") is not None:
            for k, v in self.opt_state["master"].items():
                out["master"] += shard_bytes(v, self.opt_shardings[k])
        out["total"] = out["params"] + out["slots"] + out["master"]
        return out

    # ------------------------------------------------------------ internals
    def _check_stack_decay_uniform(self, optimizer) -> None:
        """A stacked parameter gets ONE weight-decay scalar, so the
        optimizer's exclusion decision must agree across every layer in
        the stack. Divergence (e.g. a callback targeting one layer's
        autogenerated name) would otherwise silently apply the template
        layer's decision stack-wide."""
        excl = getattr(optimizer, "_wd_exclusion", None)
        if excl is None:
            return
        for key, plist in self._stack_mask_params.items():
            decisions = {bool(optimizer._wd_excluded_for_param(p))
                         for p in plist}
            if len(decisions) > 1:
                raise ValueError(
                    f"weight-decay exclusion differs across the layers "
                    f"stacked into {key!r}; pipeline stacking applies one "
                    f"decay scalar per stacked tensor. Make the exclusion "
                    f"structural (e.g. by parameter role/suffix) so it is "
                    f"uniform across identical blocks.")
            excl[key] = decisions.pop()

    def _run_entries(self, entries: List[Tuple[int, Any]], flat, x):
        """Apply prefix/suffix run_function entries functionally: parameter
        values come from ``flat``; shared (tied) entries read the OWNER's
        values so the tied weight exists once in the param dict."""
        out = x
        for idx, entry in entries:
            if isinstance(entry, _SharedCall):
                layer = entry.layer
                src = self._shared_owner[entry.key]
                rel2val = {rel: flat[f"{src}.{rel}"]
                           for rel, _ in layer.named_parameters()}
                ctx = _bind_params(layer, rel2val)
            elif isinstance(entry, Layer):
                rel2val = {rel: flat[f"{idx}.{rel}"]
                           for rel, _ in entry.named_parameters()}
                ctx = _bind_params(entry, rel2val)
            else:
                ctx = contextlib.nullcontext()
            with ctx, autograd.functional_guard():
                t = tree_to_tensors(out)
                o = entry(*t) if isinstance(t, tuple) else entry(t)
            out = tree_to_values(o)
        return out

    # -------------------------------------------------------------- running
    def __call__(self, inputs, labels) -> Tensor:
        if self._abstract:
            raise RuntimeError("abstract PipelineTrainStep holds no arrays; "
                               "use lower() / per_device_state_bytes()")
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        iv, lv = tree_to_values(inputs), tree_to_values(labels)
        iv = jax.device_put(iv, self._data_sharding)
        lv = jax.device_put(lv, self._data_sharding)
        loss, self.params, self.opt_state = self._jit_step(
            self.params, self.opt_state, lr, iv, lv)
        if isinstance(self.optimizer._lr, LRScheduler):
            self.optimizer._lr.step()
        self._step_count += 1
        return Tensor(loss, stop_gradient=True)

    # ------------------------------------------------------------ state i/o
    def sync_to_model(self) -> None:
        """Unstack the on-device params back into the PipelineLayer's
        Tensors (state_dict / eager eval / checkpoint)."""
        rf = self.pipe_layer.run_function
        named = {}
        for idx, e in self._prefix + self._suffix:
            if isinstance(e, Layer) and not isinstance(e, _SharedCall):
                for rel, p in e.named_parameters():
                    named[f"{idx}.{rel}"] = p
        for key, idx in self._shared_owner.items():
            for rel, p in self.pipe_layer.shared_layers[key].named_parameters():
                named[f"{idx}.{rel}"] = p
        for k, v in self.params.items():
            if k.startswith(_STACK_PREFIX):
                rel = k[len(_STACK_PREFIX):]
                if self._stage_counts is not None:
                    # padded uneven layout: only slots < count are real
                    for s in range(self.S):
                        for li, j in enumerate(self._stage_slots[s]):
                            p = dict(rf[j].named_parameters())[rel]
                            p._value = v[s, li]
                    continue
                if self.V > 1:   # (S, V, L, ...) -> depth order (V*S*L, ...)
                    v = jnp.swapaxes(v, 0, 1)
                    flat = v.reshape((self.V * self.S * self.L,) + v.shape[3:])
                else:
                    flat = v.reshape((self.S * self.L,) + v.shape[2:])
                for j in range(self._start, self._end):
                    p = dict(rf[j].named_parameters())[rel]
                    p._value = flat[j - self._start]
            elif k in named:
                named[k]._value = v

    def state_dict(self) -> Dict[str, Any]:
        self.sync_to_model()
        sd = self.pipe_layer.state_dict()
        sd["@opt_state"] = jax.tree.map(np.asarray, self.opt_state)
        return sd


class PipelineParallel(Layer):
    """fleet.distributed_model wrapper for pp_degree > 1 (reference class
    of the same name). ``train_batch`` keeps the reference signature."""

    def __init__(self, layers: PipelineLayer, hcg, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel expects a PipelineLayer (reference: "
                "TypeError in pipeline_parallel.py __init__)")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pc = (strategy.pipeline_configs if strategy is not None else {})
        self.accumulate_steps = int(pc.get("accumulate_steps", 1))
        self.micro_batch_size = pc.get("micro_batch_size", None)
        self.virtual_pp_degree = int(pc.get("virtual_pp_degree", 1))
        self._step: Optional[PipelineTrainStep] = None

    def forward(self, *args):
        return self._layers(*args)

    def _ensure_step(self, optimizer):
        if self._step is None:
            inner = getattr(optimizer, "_inner_opt", optimizer)
            # accumulate_steps < pp degree raises in PipelineTrainStep.__init__
            layer_v = getattr(self._layers, "num_virtual_pipeline_stages", 1)
            strat_v = self.virtual_pp_degree
            if layer_v > 1 and strat_v > 1 and layer_v != strat_v:
                raise ValueError(
                    f"conflicting virtual pipeline settings: PipelineLayer("
                    f"num_virtual_pipeline_stages={layer_v}) vs strategy "
                    f"pipeline_configs virtual_pp_degree={strat_v}")
            v = max(layer_v, strat_v)
            self._step = PipelineTrainStep(
                self._layers, inner, self._hcg.get_mesh(),
                self.accumulate_steps, remat=True, virtual_pp_degree=v)
        return self._step

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """data = [inputs, labels] for the full (global) batch; the step
        splits it into ``accumulate_steps`` microbatches."""
        inputs, labels = data
        step = self._ensure_step(optimizer)
        b = (inputs.shape[0] if hasattr(inputs, "shape") else len(inputs))
        if b % step.M != 0:
            raise ValueError(
                f"global batch {b} not divisible by accumulate_steps {step.M}")
        if self.micro_batch_size is not None:
            expect = step.M * int(self.micro_batch_size)
            if b != expect:
                raise ValueError(
                    f"global batch {b} != accumulate_steps ({step.M}) x "
                    f"micro_batch_size ({self.micro_batch_size}) = {expect}")
        loss = step(inputs, labels)
        # the step already advanced optimizer._lr; only step a scheduler
        # that is a DIFFERENT object (reference passes the optimizer's own)
        if (lr_scheduler is not None
                and lr_scheduler is not getattr(step.optimizer, "_lr", None)):
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss: bool = True):
        inputs, labels = data
        if self._step is not None:
            self._step.sync_to_model()  # eval with the TRAINED weights
        self._layers.eval()
        with autograd.no_grad():
            out = self._layers(inputs)
            if compute_loss:
                out = self._layers._loss_fn(out, labels)
        self._layers.train()
        return out

    def state_dict(self, *a, **k):
        if self._step is not None:
            self._step.sync_to_model()
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd):
        return self._layers.set_state_dict(sd)
