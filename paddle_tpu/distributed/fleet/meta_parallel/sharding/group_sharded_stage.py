"""GroupSharded stage 2/3 wrappers (reference:
python/paddle/distributed/fleet/meta_parallel/sharding/group_sharded_stage2.py
and group_sharded_stage3.py — GroupShardedStage2/GroupShardedStage3 dygraph
wrappers; GroupShardedOptimizerStage2 in group_sharded_optimizer_stage2.py).

The wrappers keep the reference's API shape (a Layer wrapping the user model,
an optimizer wrapper owning the shard) but their work is declarative: they
stamp ``_group_sharded_level`` / ``_sharding_axis`` onto model + optimizer and
(stage 3) extend each parameter's ``dist_attr`` so the jitted TrainStep stores
params sharded and GSPMD gathers on use. Forward passes straight through —
parallel==serial numerics hold by construction.
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import PartitionSpec as P

from .....nn.layer import Layer
from ...base_topology import try_get_hybrid_communicate_group
from .group_sharded_utils import resolve_sharding_axis


def _sharding_axis_for(group) -> str:
    from ....communication.group import resolve_group_axis
    axis = resolve_group_axis(group)
    if axis:
        return axis
    hcg = try_get_hybrid_communicate_group()
    if hcg is not None:
        mesh = hcg.get_mesh()
        ax = resolve_sharding_axis(mesh)
        if ax is not None:
            return ax
    return "sharding"


class GroupShardedOptimizerStage2:
    """Optimizer wrapper owning the opt-state shard (reference:
    GroupShardedOptimizerStage2 — rank-local slices + broadcast of updated
    params). Here: marks the wrapped optimizer so TrainStep shards its slot
    tree over the sharding axis; delegates everything else."""

    def __init__(self, params, optim, group=None, offload=False, device="tpu",
                 **kw):
        self._optim = optim
        self._group = group
        self.offload = offload
        optim._group_sharded_level = max(
            getattr(optim, "_group_sharded_level", 0), 1)
        optim._sharding_axis = _sharding_axis_for(group)

    def __getattr__(self, item):
        try:
            return getattr(self.__dict__["_optim"], item)
        except KeyError:
            raise AttributeError(item) from None

    # the reference exposes .step()/.clear_grad() on the wrapper
    def step(self):
        return self._optim.step()

    def clear_grad(self, *a, **k):
        return self._optim.clear_grad(*a, **k)

    def state_dict(self):
        return self._optim.state_dict()

    def set_state_dict(self, sd):
        return self._optim.set_state_dict(sd)


class GroupShardedStage2(Layer):
    """Stage-2 model wrapper: grads + optimizer state sharded (reference:
    GroupShardedStage2 — grad reduce-scatter hooks, GradStorage fusion).
    GSPMD's reduce-scatter falls out of the sharded opt-state spec; the
    wrapper passes forward through unchanged."""

    def __init__(self, layer: Layer, sharding_optimizer=None, group=None,
                 sync_buffers: bool = False, buffer_max_size: int = 2 ** 23,
                 auto_refresh_trainable: bool = True, device: str = "tpu",
                 dp_group=None):
        super().__init__()
        self._layer = layer
        self._group = group
        self._group_sharded_level = 2
        self._sharding_axis = _sharding_axis_for(group)
        opts = sharding_optimizer
        if opts is not None:
            for o in (opts if isinstance(opts, (list, tuple)) else [opts]):
                tgt = getattr(o, "_optim", o)
                tgt._group_sharded_level = 2
                tgt._sharding_axis = self._sharding_axis

    def forward(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, item):
        try:
            return super().__getattr__(item)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layer"], item)


class GroupShardedStage3(Layer):
    """Stage-3 model wrapper: params, grads and optimizer state all sharded
    (reference: GroupShardedStage3 — param segmentation, pre-forward/
    pre-backward all-gather, release after use, optional CPU offload).
    Here each param's dist_attr gains the sharding axis; TrainStep stores the
    shard and GSPMD all-gathers at each use site — the same traffic pattern,
    scheduled by XLA."""

    def __init__(self, layer: Layer, optimizer=None, group=None,
                 sync_buffers: bool = False, device: str = "tpu",
                 segment_size: int = 2 ** 20, pertrain_sync_models: bool = True,
                 offload: bool = False, sync_comm: bool = False,
                 dp_group=None, exclude_layer=None):
        super().__init__()
        self._layer = layer
        self._group = group
        self.offload = offload
        self._group_sharded_level = 3
        self._sharding_axis = _sharding_axis_for(group)
        if optimizer is not None:
            for o in (optimizer if isinstance(optimizer, (list, tuple))
                      else [optimizer]):
                tgt = o.__dict__.get("_optim", o)
                tgt._group_sharded_level = 3
                tgt._sharding_axis = self._sharding_axis
        # spec extension happens in ONE place (TrainStep, level>=3); the
        # wrapper only records which params the user excluded
        self._sharding_exclude_ids = set()
        if exclude_layer:
            for l in exclude_layer:
                for _, p in getattr(l, "named_parameters", lambda: [])():
                    self._sharding_exclude_ids.add(id(p))

    def forward(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def get_all_parameters(self, convert2cpu: bool = False):
        """Reference API: materialize full params (all-gather). Under GSPMD
        the logical value is already full; this returns the full logical
        params; sharded save/reshard lives in paddle_tpu.distributed.checkpoint."""
        return list(self._layer.parameters())

    def __getattr__(self, item):
        try:
            return super().__getattr__(item)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layer"], item)
