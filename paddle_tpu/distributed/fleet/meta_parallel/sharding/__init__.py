"""Sharding (ZeRO) meta_parallel package (reference:
python/paddle/distributed/fleet/meta_parallel/sharding/)."""

from .group_sharded_stage import (  # noqa: F401
    GroupShardedOptimizerStage2, GroupShardedStage2, GroupShardedStage3,
)
from .group_sharded_utils import (  # noqa: F401
    LEVEL_TO_STAGE, extend_spec_with_sharding, resolve_sharding_axis,
)
