"""ZeRO/GroupSharded spec machinery — GSPMD-first.

Reference: python/paddle/distributed/fleet/meta_parallel/sharding/
(GroupShardedStage2/3, GradStorage/ParamStorage fusion, offload hooks).

The reference implements ZeRO with runtime hooks: grads reduce-scattered to
owner ranks, params broadcast/all-gathered on demand, fused grad storages.
On TPU every one of those moves is a sharding DECLARATION: we extend each
parameter's PartitionSpec with the ``sharding`` mesh axis on a free dim and
let GSPMD insert the reduce-scatter (grads), the sharded update (optimizer),
and the all-gather (stage-3 param use). The stages differ only in WHICH trees
carry the extended spec:

  stage 1 ("os")     : optimizer slots + master weights
  stage 2 ("os_g")   : + gradients (reduce-scatter instead of all-reduce)
  stage 3 ("p_g_os") : + the parameters themselves (gather-on-use)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from jax.sharding import Mesh, PartitionSpec as P

#: level string (reference group_sharded_parallel API) -> numeric stage
LEVEL_TO_STAGE = {"os": 1, "os_g": 2, "p_g_os": 3}


def _axis_sizes(mesh: Mesh, entry) -> int:
    names = entry if isinstance(entry, tuple) else (entry,)
    size = 1
    for n in names:
        size *= mesh.shape[n]
    return size


def extend_spec_with_sharding(
    spec: Optional[P],
    shape: Sequence[int],
    mesh: Mesh,
    axis: str = "sharding",
) -> P:
    """Add the ZeRO ``axis`` to a (possibly TP-sharded) PartitionSpec.

    Picks the LARGEST dim the axis divides evenly, preferring free (None)
    dims; a dim already sharded (e.g. by mp) can be co-sharded when its
    per-shard extent still divides. Falls back to the original spec when
    nothing divides — a replicated scalar/LN param costs nothing anyway.
    """
    if axis not in mesh.shape or mesh.shape[axis] <= 1:
        return spec if spec is not None else P()
    size = mesh.shape[axis]
    entries = list(spec) if spec is not None else []
    entries += [None] * (len(shape) - len(entries))

    best_dim, best_extent, best_free = -1, 0, False
    for d, (e, s) in enumerate(zip(entries, shape)):
        if e is not None:
            names = e if isinstance(e, tuple) else (e,)
            if axis in names:
                return P(*entries)  # already sharded over this axis
            per_shard = s // _axis_sizes(mesh, e)
            free = False
        else:
            per_shard = s
            free = True
        if per_shard % size != 0 or per_shard < size:
            continue
        # prefer free dims; among candidates take the largest extent
        if (free, per_shard) > (best_free, best_extent) and (
                free or not best_free):
            best_dim, best_extent, best_free = d, per_shard, free
    if best_dim < 0:
        return P(*entries)
    e = entries[best_dim]
    if e is None:
        entries[best_dim] = axis
    else:
        names = e if isinstance(e, tuple) else (e,)
        entries[best_dim] = tuple(names) + (axis,)
    return P(*entries)


def resolve_sharding_axis(mesh: Mesh) -> Optional[str]:
    """The mesh axis ZeRO shards over: ``sharding`` if present (>1), else
    ``dp`` (the common TPU fusion of dp and sharding), else None."""
    for a in ("sharding", "dp"):
        if a in mesh.shape and mesh.shape[a] > 1:
            return a
    return None
