"""fleet.meta_parallel (reference: python/paddle/distributed/fleet/meta_parallel/)."""
from .parallel_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, parallel_matmul, RowParallelLinear,
    VocabParallelEmbedding,
)
from . import pp_utils  # noqa: F401
from .meta_parallel_base import (  # noqa: F401
    DataParallel, MetaParallelBase, ShardingParallel, TensorParallel,
)
from .pipeline_parallel import PipelineParallel, PipelineTrainStep  # noqa: F401
from .pp_layers import (  # noqa: F401
    LayerDesc, PipelineLayer, SegmentLayers, SharedLayerDesc,
)
from .sharding import (  # noqa: F401
    GroupShardedOptimizerStage2, GroupShardedStage2, GroupShardedStage3,
)

from .parallel_layers import RNGStatesTracker, get_rng_state_tracker  # noqa: F401
