"""fleet.meta_parallel (reference: python/paddle/distributed/fleet/meta_parallel/)."""
from .parallel_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .sharding import (  # noqa: F401
    GroupShardedOptimizerStage2, GroupShardedStage2, GroupShardedStage3,
)
