"""Zero-bubble (ZBH1) pipeline schedule.

Reference: the ZBH1 mode of
python/paddle/distributed/passes/pipeline_scheduler_pass (zero-bubble
pipeline: split each backward into B = dx, the critical path, and
W = dW, deferrable, and fill pipeline bubbles with W work).

TPU-native formulation. The other schedules here (pipeline_parallel.py)
are LOCKSTEP: a vmap over the pp-sharded stage axis runs the SAME program
on every stage each tick, with fill/drain ticks masked — masked work still
executes, so the bubble burns real compute and no schedule permutation can
recover it. Zero bubble therefore needs per-stage DIVERGENT execution,
which on TPU is ``shard_map`` over the pp axis with ``lax.cond``-gated
work units: cond executes only the taken branch at runtime, so a tick
costs max-over-stages of the unit each stage actually runs, and ticks
where a stage has no unit cost it ~nothing.

Units per (stage, microbatch):
  F  forward through the stage's L blocks (stage 0 prepends the prefix /
     embedding; stage S-1 stores y for its B unit)
  B  dx-only backward (stage S-1 first runs suffix+loss and seeds the
     gradient; stage 0 stores its dx for the deferred prefix backward);
     sends dx down the ring
  W  the deferred parameter gradient (stage 0's W also runs the prefix
     backward) — the ZBH1 split
A greedy static scheduler (numpy, trace time) assigns at most one unit
per stage per tick with priority B > F > W — W fills what would be bubble
ticks. Ring messages (activations up, dx down) move via ppermute every
tick and are stashed into per-microbatch buffers on arrival, driven by
static stash tables (a message's slot is known from the schedule), so a
busy receiver can consume it any later tick.

Exactness: loss is computed per microbatch at stage S-1 and averaged —
mean of equal-size microbatch means == the full-batch mean for token-mean
criteria (suffixes must be per-token, which final-norm + head are).
Parity vs the serial model is pinned by tests/test_zbh1.py.

Cost model (per microbatch per stage, F = one forward): F + (Fr + Bdx)
+ (Fr + Bdw) ~ 5F vs the lockstep schedules' 4F — the extra forward
recompute is the price of decoupling W from B in a pure functional
program. The payoff is scheduling freedom: steady-state ticks cost
~max(2F) and fill/drain ticks shrink toward zero instead of burning
masked slots, so wall-clock beats lockstep once the bubble fraction
(S-1)/(M+S-1) outweighs the extra recompute.

v1 scope: mesh with only a "pp" axis, V == 1, no ZeRO composition.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P



def zbh1_schedule(S: int, M: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Greedy ZBH1 tables: (F, B, W), each (T, S), holding the microbatch
    index a stage processes at that tick, or -1. Priority B > F > W."""
    f_time = np.full((S, M), -1)
    b_time = np.full((S, M), -1)
    next_f = [0] * S
    next_b = [0] * S
    next_w = [0] * S
    rows_f, rows_b, rows_w = [], [], []
    t = 0
    cap = 6 * (M + S) + 8
    while any(n < M for n in next_w) and t < cap:
        rf, rb, rw = [-1] * S, [-1] * S, [-1] * S
        for s in range(S):
            m = next_b[s]
            b_ready = m < M and (
                (s == S - 1 and 0 <= f_time[s][m] < t)
                or (s < S - 1 and 0 <= b_time[s + 1][m] < t))
            mf = next_f[s]
            f_ready = mf < M and (s == 0 or 0 <= f_time[s - 1][mf] < t)
            if b_ready:
                rb[s] = m
                b_time[s][m] = t
                next_b[s] += 1
            elif f_ready:
                rf[s] = mf
                f_time[s][mf] = t
                next_f[s] += 1
            elif next_w[s] < next_b[s]:
                rw[s] = next_w[s]
                next_w[s] += 1
        rows_f.append(rf)
        rows_b.append(rb)
        rows_w.append(rw)
        t += 1
    if any(n < M for n in next_w):
        raise RuntimeError(f"zbh1 schedule did not complete in {cap} ticks")
    return (np.asarray(rows_f, np.int32), np.asarray(rows_b, np.int32),
            np.asarray(rows_w, np.int32))


def _stash_tables(Ft, Bt, S):
    """stash_f[t][s]: slot where the activation arriving at stage s at the
    START of tick t belongs (= what s-1 forwarded at t-1); stash_b the
    same for dx arriving from s+1. -1 = nothing arrived."""
    T = Ft.shape[0]
    sf = np.full((T, S), -1, np.int32)
    sb = np.full((T, S), -1, np.int32)
    for t in range(1, T):
        for s in range(S):
            if s > 0:
                sf[t][s] = Ft[t - 1][s - 1]
            if s < S - 1:
                sb[t][s] = Bt[t - 1][s + 1]
    return sf, sb


def _masked_store(buf, idx, val, pred):
    """buf[idx] = val where pred (idx may be -1 => no-op via pred)."""
    slot = jnp.maximum(idx, 0)
    prev = jax.lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)
    new = jnp.where(jnp.logical_and(pred, idx >= 0), val, prev)
    return jax.lax.dynamic_update_index_in_dim(buf, new, slot, 0)


def build_zbh1_loss_and_grads(
        mesh: Mesh, S: int, M: int,
        block_rels: List[str],
        template,
        prefix_apply: Callable,      # (prefix_params, ids_mb) -> x
        suffix_loss: Callable,       # (suffix_params, y_mb, labels_mb) -> loss
        act_sds: jax.ShapeDtypeStruct,
        remat: bool = True,
        dp_axis: str = None):
    """Returns f(stacked_tuple, prefix_params, suffix_params, ids, labels)
    -> (loss, stacked_grads_tuple, prefix_grads, suffix_grads). ids/labels
    are (M, mb, ...); stacked leaves are (S, L, ...) pp-sharded. With
    ``dp_axis`` the microbatch dim is additionally dp-sharded (params
    replicated over dp): loss and grads are pmean'd over dp — standard
    data parallelism composed INSIDE the manual region, so the pp ring
    stays per-dp-slice and the dp reduction is one collective at the
    end. ``act_sds`` must describe the LOCAL (per-dp-shard) activation."""

    Ft, Bt, Wt = zbh1_schedule(S, M)
    sf_tab, sb_tab = _stash_tables(Ft, Bt, S)
    ring_up = [(i, (i + 1) % S) for i in range(S)]
    ring_dn = [(i, (i - 1) % S) for i in range(S)]

    from .pipeline_parallel import make_stage_fn
    stage_fn = make_stage_fn(template, block_rels, remat)

    def kernel(stacked, prefix_params, suffix_params, ids, labels):
        local = tuple(a[0] for a in stacked)     # drop the stage dim
        s_idx = jax.lax.axis_index("pp")
        is_first = s_idx == 0
        is_last = s_idx == S - 1

        zbuf = jnp.zeros((M,) + tuple(act_sds.shape), act_sds.dtype)
        X = zbuf                                  # stage inputs, M slots
        Y = zbuf                                  # last-stage outputs
        G = zbuf                                  # stage-output grads
        DX0 = zbuf                                # stage-0 dx (prefix bwd)
        up = jnp.zeros(tuple(act_sds.shape), act_sds.dtype)
        dn = jnp.zeros(tuple(act_sds.shape), act_sds.dtype)
        loss_acc = jnp.zeros((), jnp.float32)
        f32z = lambda tree: jax.tree.map(
            lambda a: jnp.zeros(a.shape, jnp.float32), tree)
        dW, dPre, dSuf = f32z(local), f32z(prefix_params), f32z(suffix_params)

        def f_unit(op):
            m, X, Y, up = op

            def from_prefix(m):
                return prefix_apply(
                    prefix_params, jax.lax.dynamic_index_in_dim(
                        ids, m, 0, keepdims=False)).astype(up.dtype)

            def from_stash(m):
                return jax.lax.dynamic_index_in_dim(X, m, 0, keepdims=False)

            x = jax.lax.cond(is_first, from_prefix, from_stash, m)
            X = jax.lax.dynamic_update_index_in_dim(X, x, m, 0)
            y = stage_fn(local, x)
            Y = _masked_store(Y, m, y, is_last)
            return X, Y, y

        def b_unit(op):
            m, X, Y, G, loss_acc, dSuf, DX0 = op
            x = jax.lax.dynamic_index_in_dim(X, m, 0, keepdims=False)

            def seed_from_loss(op2):
                y, lab, dSuf = op2
                # seed 1/M scales both dSuf and g so the sum is the mean
                lval, both_vjp = jax.vjp(
                    lambda sp, yy: suffix_loss(sp, yy, lab),
                    suffix_params, y)
                dsuf_m, g = both_vjp(jnp.ones((), lval.dtype) / M)
                dSuf = jax.tree.map(lambda a, d: a + d.astype(a.dtype),
                                    dSuf, dsuf_m)
                return g.astype(x.dtype), lval.astype(jnp.float32), dSuf

            def seed_from_ring(op2):
                y, lab, dSuf = op2
                g = jax.lax.dynamic_index_in_dim(G, m, 0, keepdims=False)
                return g, jnp.zeros((), jnp.float32), dSuf

            y_m = jax.lax.dynamic_index_in_dim(Y, m, 0, keepdims=False)
            lab_m = jax.lax.dynamic_index_in_dim(labels, m, 0,
                                                 keepdims=False)
            g, lval, dSuf = jax.lax.cond(
                is_last, seed_from_loss, seed_from_ring, (y_m, lab_m, dSuf))
            loss_acc = loss_acc + lval / M
            G = jax.lax.dynamic_update_index_in_dim(G, g, m, 0)
            _, x_vjp = jax.vjp(lambda xx: stage_fn(local, xx), x)
            (dx,) = x_vjp(g)
            DX0 = _masked_store(DX0, m, dx, is_first)
            return G, loss_acc, dSuf, DX0, dx

        def w_unit(op):
            m, X, G, DX0, dW, dPre = op
            x = jax.lax.dynamic_index_in_dim(X, m, 0, keepdims=False)
            g = jax.lax.dynamic_index_in_dim(G, m, 0, keepdims=False)
            _, p_vjp = jax.vjp(lambda lp: stage_fn(lp, x), local)
            (dw_m,) = p_vjp(g)
            dW = jax.tree.map(lambda a, d: a + d.astype(a.dtype), dW, dw_m)

            def prefix_bwd(op2):
                dPre, = op2
                dxin = jax.lax.dynamic_index_in_dim(DX0, m, 0,
                                                    keepdims=False)
                _, pre_vjp = jax.vjp(
                    lambda pp: prefix_apply(
                        pp, jax.lax.dynamic_index_in_dim(
                            ids, m, 0, keepdims=False)).astype(dxin.dtype),
                    prefix_params)
                (dpre_m,) = pre_vjp(dxin)
                return (jax.tree.map(lambda a, d: a + d.astype(a.dtype),
                                     dPre, dpre_m),)

            (dPre,) = jax.lax.cond(is_first, prefix_bwd,
                                   lambda op2: op2, (dPre,))
            return dW, dPre

        def tick(carry, xs):
            (X, Y, G, DX0, up, dn, loss_acc, dW, dPre, dSuf) = carry
            rf, rb, rw, sf, sb = xs
            pick = lambda row: row[s_idx]
            mf, mb_, mw = pick(rf), pick(rb), pick(rw)
            # stash last tick's ring arrivals into their static slots
            X = _masked_store(X, pick(sf), up, True)
            G = _masked_store(G, pick(sb), dn, True)

            X, Y, y_out = jax.lax.cond(
                mf >= 0, f_unit,
                lambda op: (op[1], op[2], jnp.zeros_like(op[3])),
                (jnp.maximum(mf, 0), X, Y, up))

            G, loss_acc, dSuf, DX0, dx_out = jax.lax.cond(
                mb_ >= 0, b_unit,
                lambda op: (op[3], op[4], op[5], op[6],
                            jnp.zeros_like(up)),
                (jnp.maximum(mb_, 0), X, Y, G, loss_acc, dSuf, DX0))

            dW, dPre = jax.lax.cond(
                mw >= 0, w_unit, lambda op: (op[4], op[5]),
                (jnp.maximum(mw, 0), X, G, DX0, dW, dPre))

            up = jax.lax.ppermute(y_out, "pp", ring_up)
            dn = jax.lax.ppermute(dx_out, "pp", ring_dn)
            return (X, Y, G, DX0, up, dn, loss_acc, dW, dPre, dSuf), None

        carry = (X, Y, G, DX0, up, dn, loss_acc, dW, dPre, dSuf)
        carry = jax.tree.map(
            lambda a: jax.lax.pcast(a, ("pp",), to="varying"), carry)
        carry, _ = jax.lax.scan(
            tick, carry,
            tuple(jnp.asarray(t) for t in (Ft, Bt, Wt, sf_tab, sb_tab)))
        (X, Y, G, DX0, up, dn, loss_acc, dW, dPre, dSuf) = carry

        loss = jax.lax.psum(jnp.where(is_last, loss_acc, 0.0), "pp")
        dPre = jax.tree.map(lambda a: jax.lax.psum(
            jnp.where(is_first, a, jnp.zeros_like(a)), "pp"), dPre)
        dSuf = jax.tree.map(lambda a: jax.lax.psum(
            jnp.where(is_last, a, jnp.zeros_like(a)), "pp"), dSuf)
        if dp_axis is not None:
            # each dp shard computed the mean loss over ITS tokens; the
            # global mean (and its gradient) is the dp-mean of those
            loss = jax.lax.pmean(loss, dp_axis)
            dW = jax.tree.map(lambda a: jax.lax.pmean(a, dp_axis), dW)
            dPre = jax.tree.map(lambda a: jax.lax.pmean(a, dp_axis), dPre)
            dSuf = jax.tree.map(lambda a: jax.lax.pmean(a, dp_axis), dSuf)
        dW = jax.tree.map(lambda a: a[None], dW)   # re-add the stage dim
        return loss, dW, dPre, dSuf

    def loss_and_grads(stacked_tuple, prefix_params, suffix_params,
                       ids, labels):
        data_spec = P(None, dp_axis) if dp_axis else P()
        in_specs = (
            tuple(P("pp") for _ in stacked_tuple),
            jax.tree.map(lambda _: P(), prefix_params),
            jax.tree.map(lambda _: P(), suffix_params),
            data_spec, data_spec)
        out_specs = (
            P(),
            tuple(P("pp") for _ in stacked_tuple),
            jax.tree.map(lambda _: P(), prefix_params),
            jax.tree.map(lambda _: P(), suffix_params))
        return jax.shard_map(kernel, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)(
            stacked_tuple, prefix_params, suffix_params, ids, labels)

    return loss_and_grads
