"""Zero-bubble (ZBH1) pipeline schedule.

Reference: the ZBH1 mode of
python/paddle/distributed/passes/pipeline_scheduler_pass (zero-bubble
pipeline: split each backward into B = dx, the critical path, and
W = dW, deferrable, and fill pipeline bubbles with W work).

TPU-native formulation. The other schedules here (pipeline_parallel.py)
are LOCKSTEP: a vmap over the pp-sharded stage axis runs the SAME program
on every stage each tick, with fill/drain ticks masked — masked work still
executes, so the bubble burns real compute and no schedule permutation can
recover it. Zero bubble therefore needs per-stage DIVERGENT execution,
which on TPU is ``shard_map`` over the pp axis with ``lax.cond``-gated
work units: cond executes only the taken branch at runtime, so a tick
costs max-over-stages of the unit each stage actually runs, and ticks
where a stage has no unit cost it ~nothing.

Units per (stage, microbatch):
  F  forward through the stage's L blocks (stage 0 prepends the prefix /
     embedding; stage S-1 stores y for its B unit)
  B  dx-only backward (stage S-1 first runs suffix+loss and seeds the
     gradient; stage 0 stores its dx for the deferred prefix backward);
     sends dx down the ring
  W  the deferred parameter gradient (stage 0's W also runs the prefix
     backward) — the ZBH1 split
A greedy static scheduler (numpy, trace time) assigns at most one unit
per stage per tick with priority B > F > W — W fills what would be bubble
ticks. Ring messages (activations up, dx down) move via ppermute every
tick and are stashed into per-microbatch buffers on arrival, driven by
static stash tables (a message's slot is known from the schedule), so a
busy receiver can consume it any later tick.

Exactness: loss is computed per microbatch at stage S-1 and averaged —
mean of equal-size microbatch means == the full-batch mean for token-mean
criteria (suffixes must be per-token, which final-norm + head are).
Parity vs the serial model is pinned by tests/test_zbh1.py.

Cost model (per microbatch per stage, F = one forward): F + (Fr + Bdx)
+ (Fr + Bdw) ~ 5F vs the lockstep schedules' 4F — the extra forward
recompute is the price of decoupling W from B in a pure functional
program. The payoff is scheduling freedom: steady-state ticks cost
~max(2F) and fill/drain ticks shrink toward zero instead of burning
masked slots, so wall-clock beats lockstep once the bubble fraction
(S-1)/(M+S-1) outweighs the extra recompute.

Composition (round 4 lifts the v1 scope):
  - tied/shared layers: the tied weights ride as a third replicated param
    group ``shared_params`` visible to BOTH phases; stage 0 accumulates
    the prefix-side contribution (in W's deferred prefix backward) and
    stage S-1 the suffix-side one (in B's loss vjp), summed by the final
    masked psum — the cross-phase gradient routing the reference's shared
    comm group performs with an allreduce.
  - mp (tensor parallel): the shard_map is manual over the WHOLE mesh
    (check_vma=False) — GSPMD-auto collectives inside the divergent
    lax.cond units are unsound (stages take different branches,
    desynchronizing compiler-inserted collectives; observed as an XLA
    rendezvous deadlock). The TP layers detect manual mp via
    ``_manual_axis()`` and switch to explicit Megatron f/g collectives
    (mp_layers._mp_copy/_mp_reduce), which ARE sound inside units:
    every member of an mp group shares its pp stage and hence its
    branch. A NEW TP layer must get the same treatment — GSPMD will
    not handle it here.
  - ZeRO: levels 1/2 (optimizer-state / gradient sharding) compose — the
    functional optimizer update and the grad resharding happen OUTSIDE
    the manual region. Level 3 (param sharding) stays rejected: P()
    in_specs would all-gather the full parameter state at shard_map
    entry every step with no GSPMD control over the gather's placement.

Remaining v1 scope: V == 1 (no interleaved VPP), no abstract lowering.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P



def zbh1_schedule(S: int, M: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Greedy ZBH1 tables: (F, B, W), each (T, S), holding the microbatch
    index a stage processes at that tick, or -1. Priority B > F > W."""
    f_time = np.full((S, M), -1)
    b_time = np.full((S, M), -1)
    next_f = [0] * S
    next_b = [0] * S
    next_w = [0] * S
    rows_f, rows_b, rows_w = [], [], []
    t = 0
    cap = 6 * (M + S) + 8
    while any(n < M for n in next_w) and t < cap:
        rf, rb, rw = [-1] * S, [-1] * S, [-1] * S
        for s in range(S):
            m = next_b[s]
            b_ready = m < M and (
                (s == S - 1 and 0 <= f_time[s][m] < t)
                or (s < S - 1 and 0 <= b_time[s + 1][m] < t))
            mf = next_f[s]
            f_ready = mf < M and (s == 0 or 0 <= f_time[s - 1][mf] < t)
            if b_ready:
                rb[s] = m
                b_time[s][m] = t
                next_b[s] += 1
            elif f_ready:
                rf[s] = mf
                f_time[s][mf] = t
                next_f[s] += 1
            elif next_w[s] < next_b[s]:
                rw[s] = next_w[s]
                next_w[s] += 1
        rows_f.append(rf)
        rows_b.append(rb)
        rows_w.append(rw)
        t += 1
    if any(n < M for n in next_w):
        raise RuntimeError(f"zbh1 schedule did not complete in {cap} ticks")
    return (np.asarray(rows_f, np.int32), np.asarray(rows_b, np.int32),
            np.asarray(rows_w, np.int32))


def _stash_tables(Ft, Bt, S):
    """stash_f[t][s]: slot where the activation arriving at stage s at the
    START of tick t belongs (= what s-1 forwarded at t-1); stash_b the
    same for dx arriving from s+1. -1 = nothing arrived."""
    T = Ft.shape[0]
    sf = np.full((T, S), -1, np.int32)
    sb = np.full((T, S), -1, np.int32)
    for t in range(1, T):
        for s in range(S):
            if s > 0:
                sf[t][s] = Ft[t - 1][s - 1]
            if s < S - 1:
                sb[t][s] = Bt[t - 1][s + 1]
    return sf, sb


def _masked_store(buf, idx, val, pred):
    """buf[idx] = val where pred (idx may be -1 => no-op via pred)."""
    slot = jnp.maximum(idx, 0)
    prev = jax.lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)
    new = jnp.where(jnp.logical_and(pred, idx >= 0), val, prev)
    return jax.lax.dynamic_update_index_in_dim(buf, new, slot, 0)


def build_zbh1_loss_and_grads(
        mesh: Mesh, S: int, M: int,
        block_rels: List[str],
        template,
        prefix_apply: Callable,   # (prefix_params, shared_params, ids) -> x
        suffix_loss: Callable,    # (suffix_params, shared_params, y, lab) -> l
        act_sds: jax.ShapeDtypeStruct,
        remat: bool = True,
        dp_axis: str = None,
        stacked_specs=None,          # per-block_rel P, e.g. P('pp',None,'mp')
        pre_specs=None, suf_specs=None, shr_specs=None):
    """Returns f(stacked_tuple, prefix_params, suffix_params, shared_params,
    ids, labels) -> (loss, stacked_grads_tuple, prefix_grads, suffix_grads,
    shared_grads). ``shared_params``: tied weights read by both phases
    (empty dict when none) — their gradient sums the stage-0 prefix-side
    and stage-(S-1) suffix-side contributions. ids/labels
    are (M, mb, ...); stacked leaves are (S, L, ...) pp-sharded. With
    ``dp_axis`` the microbatch dim is additionally dp-sharded (params
    replicated over dp): loss and grads are pmean'd over dp — standard
    data parallelism composed INSIDE the manual region, so the pp ring
    stays per-dp-slice and the dp reduction is one collective at the
    end. ``act_sds`` must describe the LOCAL (per-dp-shard) activation."""

    if stacked_specs is None:
        stacked_specs = [P("pp") for _ in block_rels]
    pre_specs = pre_specs or {}
    suf_specs = suf_specs or {}
    shr_specs = shr_specs or {}

    def spec_axes(spec):
        out = set()
        for entry in spec:
            if entry is None:
                continue
            out.update(entry if isinstance(entry, tuple) else (entry,))
        return out

    # tensor-parallel (and any other) axes named by param specs become
    # MANUAL axes of the engine: GSPMD-auto collectives inside divergent
    # lax.cond units are unsound (different pp stages take different
    # branches, desynchronizing the compiler-inserted collective schedule
    # — observed as an XLA rendezvous deadlock), while explicit TP
    # collectives are sound because every member of an mp group shares
    # its stage and therefore its branch. The TP layers switch to their
    # explicit-collective path via _manual_axis().
    tp_axes = set()
    for sp in list(stacked_specs) + list(pre_specs.values()) \
            + list(suf_specs.values()) + list(shr_specs.values()):
        tp_axes |= spec_axes(sp)
    tp_axes -= {"pp", dp_axis}
    tp_axes = tuple(sorted(tp_axes))

    Ft, Bt, Wt = zbh1_schedule(S, M)
    sf_tab, sb_tab = _stash_tables(Ft, Bt, S)
    ring_up = [(i, (i + 1) % S) for i in range(S)]
    ring_dn = [(i, (i - 1) % S) for i in range(S)]

    from .pipeline_parallel import make_stage_fn
    stage_fn = make_stage_fn(template, block_rels, remat)

    # axes the kernel is manual over — every per-stage value varies on
    # them (vma); cond branches and the scan carry must agree on this
    vary_axes = ("pp",) + ((dp_axis,) if dp_axis else ()) + tp_axes

    def _vary(x):
        """Promote x to varying over the engine's manual axes (idempotent
        per axis) — cond branches and the scan carry must agree on vma.
        jax versions without vma tracking (< 0.6) have no varying types
        to reconcile, so x passes through."""
        if not hasattr(jax, "typeof") or not hasattr(jax.lax, "pcast"):
            return x
        missing = tuple(a for a in vary_axes
                        if a not in jax.typeof(x).vma)
        return jax.lax.pcast(x, missing, to="varying") if missing else x

    def kernel(stacked, prefix_params, suffix_params, shared_params,
               ids, labels):
        local = tuple(a[0] for a in stacked)     # drop the stage dim
        s_idx = jax.lax.axis_index("pp")
        is_first = s_idx == 0
        is_last = s_idx == S - 1

        zbuf = jnp.zeros((M,) + tuple(act_sds.shape), act_sds.dtype)
        X = zbuf                                  # stage inputs, M slots
        Y = zbuf                                  # last-stage outputs
        G = zbuf                                  # stage-output grads
        DX0 = zbuf                                # stage-0 dx (prefix bwd)
        up = jnp.zeros(tuple(act_sds.shape), act_sds.dtype)
        dn = jnp.zeros(tuple(act_sds.shape), act_sds.dtype)
        loss_acc = jnp.zeros((), jnp.float32)
        f32z = lambda tree: jax.tree.map(
            lambda a: jnp.zeros(a.shape, jnp.float32), tree)
        dW, dPre, dSuf = f32z(local), f32z(prefix_params), f32z(suffix_params)
        # tied-weight grads, accumulated on different stages per phase
        dShrP, dShrS = f32z(shared_params), f32z(shared_params)

        def f_unit(op):
            m, X, Y, up = op

            def from_prefix(m):
                return _vary(prefix_apply(
                    prefix_params, shared_params,
                    jax.lax.dynamic_index_in_dim(
                        ids, m, 0, keepdims=False)).astype(up.dtype))

            def from_stash(m):
                return jax.lax.dynamic_index_in_dim(X, m, 0, keepdims=False)

            x = jax.lax.cond(is_first, from_prefix, from_stash, m)
            X = jax.lax.dynamic_update_index_in_dim(X, x, m, 0)
            y = stage_fn(local, x)
            Y = _masked_store(Y, m, y, is_last)
            return X, Y, y

        def b_unit(op):
            m, X, Y, G, loss_acc, dSuf, dShrS, DX0 = op
            x = jax.lax.dynamic_index_in_dim(X, m, 0, keepdims=False)

            def seed_from_loss(op2):
                y, lab, dSuf, dShrS = op2
                # seed 1/M scales dSuf/dShrS and g so the sum is the mean
                lval, both_vjp = jax.vjp(
                    lambda sp, sh, yy: suffix_loss(sp, sh, yy, lab),
                    suffix_params, shared_params, y)
                # the cotangent must carry lval's vma (varying over the
                # manual axes when check_vma=True) — derive it from lval;
                # the value is exactly 1/M: seed scales dSuf/dShrS and g
                # so the sum over microbatches is the mean
                dsuf_m, dshr_m, g = both_vjp((lval * 0 + 1) / M)
                dSuf = jax.tree.map(lambda a, d: a + d.astype(a.dtype),
                                    dSuf, dsuf_m)
                dShrS = jax.tree.map(lambda a, d: a + d.astype(a.dtype),
                                     dShrS, dshr_m)
                return (g.astype(x.dtype), lval.astype(jnp.float32), dSuf,
                        dShrS)

            def seed_from_ring(op2):
                y, lab, dSuf, dShrS = op2
                g = jax.lax.dynamic_index_in_dim(G, m, 0, keepdims=False)
                return g, _vary(jnp.zeros((), jnp.float32)), dSuf, dShrS

            y_m = jax.lax.dynamic_index_in_dim(Y, m, 0, keepdims=False)
            lab_m = jax.lax.dynamic_index_in_dim(labels, m, 0,
                                                 keepdims=False)
            g, lval, dSuf, dShrS = jax.lax.cond(
                is_last, seed_from_loss, seed_from_ring,
                (y_m, lab_m, dSuf, dShrS))
            loss_acc = loss_acc + lval / M
            G = jax.lax.dynamic_update_index_in_dim(G, g, m, 0)
            _, x_vjp = jax.vjp(lambda xx: stage_fn(local, xx), x)
            (dx,) = x_vjp(g)
            DX0 = _masked_store(DX0, m, dx, is_first)
            return G, loss_acc, dSuf, dShrS, DX0, dx

        def w_unit(op):
            m, X, G, DX0, dW, dPre, dShrP = op
            x = jax.lax.dynamic_index_in_dim(X, m, 0, keepdims=False)
            g = jax.lax.dynamic_index_in_dim(G, m, 0, keepdims=False)
            _, p_vjp = jax.vjp(lambda lp: stage_fn(lp, x), local)
            (dw_m,) = p_vjp(g)
            dW = jax.tree.map(lambda a, d: a + d.astype(a.dtype), dW, dw_m)

            def prefix_bwd(op2):
                dPre, dShrP = op2
                dxin = jax.lax.dynamic_index_in_dim(DX0, m, 0,
                                                    keepdims=False)
                _, pre_vjp = jax.vjp(
                    lambda pp, sh: prefix_apply(
                        pp, sh, jax.lax.dynamic_index_in_dim(
                            ids, m, 0, keepdims=False)).astype(dxin.dtype),
                    prefix_params, shared_params)
                dpre_m, dshr_m = pre_vjp(dxin)
                return (jax.tree.map(lambda a, d: a + d.astype(a.dtype),
                                     dPre, dpre_m),
                        jax.tree.map(lambda a, d: a + d.astype(a.dtype),
                                     dShrP, dshr_m))

            dPre, dShrP = jax.lax.cond(is_first, prefix_bwd,
                                       lambda op2: op2, (dPre, dShrP))
            return dW, dPre, dShrP

        def tick(carry, xs):
            (X, Y, G, DX0, up, dn, loss_acc,
             dW, dPre, dSuf, dShrP, dShrS) = carry
            rf, rb, rw, sf, sb = xs
            pick = lambda row: row[s_idx]
            mf, mb_, mw = pick(rf), pick(rb), pick(rw)
            # stash last tick's ring arrivals into their static slots
            X = _masked_store(X, pick(sf), up, True)
            G = _masked_store(G, pick(sb), dn, True)

            X, Y, y_out = jax.lax.cond(
                mf >= 0, f_unit,
                lambda op: (op[1], op[2], jnp.zeros_like(op[3])),
                (jnp.maximum(mf, 0), X, Y, up))

            G, loss_acc, dSuf, dShrS, DX0, dx_out = jax.lax.cond(
                mb_ >= 0, b_unit,
                lambda op: (op[3], op[4], op[5], op[6], op[7],
                            jnp.zeros_like(up)),
                (jnp.maximum(mb_, 0), X, Y, G, loss_acc, dSuf, dShrS, DX0))

            dW, dPre, dShrP = jax.lax.cond(
                mw >= 0, w_unit, lambda op: (op[4], op[5], op[6]),
                (jnp.maximum(mw, 0), X, G, DX0, dW, dPre, dShrP))

            up = jax.lax.ppermute(y_out, "pp", ring_up)
            dn = jax.lax.ppermute(dx_out, "pp", ring_dn)
            return (X, Y, G, DX0, up, dn, loss_acc,
                    dW, dPre, dSuf, dShrP, dShrS), None

        carry = (X, Y, G, DX0, up, dn, loss_acc,
                 dW, dPre, dSuf, dShrP, dShrS)
        carry = jax.tree.map(_vary, carry)
        carry, _ = jax.lax.scan(
            tick, carry,
            tuple(jnp.asarray(t) for t in (Ft, Bt, Wt, sf_tab, sb_tab)))
        (X, Y, G, DX0, up, dn, loss_acc,
         dW, dPre, dSuf, dShrP, dShrS) = carry

        loss = jax.lax.psum(jnp.where(is_last, loss_acc, 0.0), "pp")
        dPre = jax.tree.map(lambda a: jax.lax.psum(
            jnp.where(is_first, a, jnp.zeros_like(a)), "pp"), dPre)
        dSuf = jax.tree.map(lambda a: jax.lax.psum(
            jnp.where(is_last, a, jnp.zeros_like(a)), "pp"), dSuf)
        # tied weights: prefix-side contribution lives on stage 0, the
        # suffix-side one on stage S-1 — one masked psum sums both (and
        # both land on the same device when S == 1)
        dShr = jax.tree.map(
            lambda ap, as_: jax.lax.psum(
                jnp.where(is_first, ap, jnp.zeros_like(ap))
                + jnp.where(is_last, as_, jnp.zeros_like(as_)), "pp"),
            dShrP, dShrS)
        if dp_axis is not None:
            # each dp shard computed the mean loss over ITS tokens; the
            # global mean (and its gradient) is the dp-mean of those
            loss = jax.lax.pmean(loss, dp_axis)
            dW = jax.tree.map(lambda a: jax.lax.pmean(a, dp_axis), dW)
            dPre = jax.tree.map(lambda a: jax.lax.pmean(a, dp_axis), dPre)
            dSuf = jax.tree.map(lambda a: jax.lax.pmean(a, dp_axis), dSuf)
            dShr = jax.tree.map(lambda a: jax.lax.pmean(a, dp_axis), dShr)
        if tp_axes:
            # grads of params NOT sharded over a tp axis are numerically
            # replicated across it (activations re-replicate at each row
            # psum); the pmean is an identity that discharges the
            # varying-axis bookkeeping so P()-style out_specs hold
            def drop_tp(a, spec):
                for ax in tp_axes:
                    if ax not in spec_axes(spec):
                        a = jax.lax.pmean(a, ax)
                return a
            loss = drop_tp(loss, P())
            dW = tuple(drop_tp(a, sp)
                       for a, sp in zip(dW, [P(*sp[1:]) for sp in
                                             stacked_specs]))
            dPre = {k: drop_tp(a, pre_specs.get(k, P()))
                    for k, a in dPre.items()}
            dSuf = {k: drop_tp(a, suf_specs.get(k, P()))
                    for k, a in dSuf.items()}
            dShr = {k: drop_tp(a, shr_specs.get(k, P()))
                    for k, a in dShr.items()}
        dW = jax.tree.map(lambda a: a[None], dW)   # re-add the stage dim
        return loss, dW, dPre, dSuf, dShr

    def loss_and_grads(stacked_tuple, prefix_params, suffix_params,
                       shared_params, ids, labels):
        data_spec = P(None, dp_axis) if dp_axis else P()

        def dict_specs(specs, tree):
            return {k: specs.get(k, P()) for k in tree}

        in_specs = (
            tuple(stacked_specs),
            dict_specs(pre_specs, prefix_params),
            dict_specs(suf_specs, suffix_params),
            dict_specs(shr_specs, shared_params),
            data_spec, data_spec)
        out_specs = (
            P(),
            tuple(stacked_specs),
            dict_specs(pre_specs, prefix_params),
            dict_specs(suf_specs, suffix_params),
            dict_specs(shr_specs, shared_params))
        # manual over the WHOLE mesh with check_vma=False: the engine's
        # vjp structure computes LOCAL grads inside divergent cond
        # branches and reduces them with the explicit masked psums at the
        # end. check_vma=True would auto-insert transpose collectives
        # INSIDE the divergent branches (unsound — different pp stages
        # take different branches, observed as an XLA rendezvous
        # deadlock). The TP layers' manual f/g ops carry the only
        # collectives that belong inside units, and they are sound
        # because an mp group shares its stage and hence its branch.
        # Mesh axes named by NO spec (e.g. mp with a non-TP model, or
        # size-1 sharding/sep axes) replicate the work — sound, since
        # full-manual means no GSPMD could use them anyway.
        return jax.shard_map(kernel, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)(
            stacked_tuple, prefix_params, suffix_params, shared_params,
            ids, labels)

    return loss_and_grads
