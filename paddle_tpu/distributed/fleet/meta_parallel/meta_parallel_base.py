"""MetaParallelBase + TensorParallel / DataParallel / ShardingParallel
wrappers (reference: .../meta_parallel/meta_parallel_base.py,
tensor_parallel.py, sharding_parallel.py and base/dygraph/parallel.py's
DataParallel over EagerReducer).

On TPU these wrappers carry no runtime hooks of their own: TP layers already
annotate their params with PartitionSpecs, DP/sharding gradient sync falls
out of GSPMD when the jitted train step shards the batch over dp — XLA emits
the bucketed all-reduce/reduce-scatter the reference implements by hand in
reducer.cc. The classes exist so ``fleet.distributed_model`` returns the
reference's types and so strategy metadata (broadcast of initial params
across dp, sharded-model markers) has a place to live.
"""

from __future__ import annotations

from ....nn.layer import Layer


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


class TensorParallel(MetaParallelBase):
    """mp_degree > 1, pp_degree == 1. Param shardings come from the layer
    annotations (mp_layers.py); nothing to do at wrap time beyond marking."""

    def _prepare_for_model(self):
        self._layers._is_tensor_parallel = True


class ShardingParallel(MetaParallelBase):
    def _prepare_for_model(self):
        self._layers._is_sharding_parallel = True


class DataParallel(MetaParallelBase):
    """Plain DP (reference: paddle.DataParallel over EagerReducer buckets).
    Gradient averaging over dp is a by-product of GSPMD batch sharding in
    the train step; ``find_unused_parameters``/bucket knobs are accepted for
    API compatibility and ignored."""

    def __init__(self, layers, hcg=None, strategy=None,
                 comm_buffer_size: int = 25, last_comm_buffer_size: int = 1,
                 find_unused_parameters: bool = False, group=None):
        super().__init__(layers, hcg, strategy)

    def _prepare_for_model(self):
        self._layers._is_data_parallel = True

    def scale_loss(self, loss):
        return loss  # GSPMD mean over the dp-sharded batch already averages

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()
