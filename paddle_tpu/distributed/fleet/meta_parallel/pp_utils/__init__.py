from . import p2p_communication  # noqa: F401
