"""p2p_communication — stage-to-stage transfer API parity.

Reference: .../meta_parallel/pp_utils/p2p_communication.py: NCCL send/recv
pairs with a ``SendRecvMeta`` shape/dtype handshake (the receiver must
allocate before NCCL recv), batched isend/irecv.

On TPU the production path does NOT use these: the pipelined train step is
one SPMD program whose stage shift is an XLA collective-permute (see
pipeline_parallel.py), so shapes are static and no handshake exists. This
module keeps the reference surface for user code/tests that drive p2p
manually — each call forwards to the eager collective facade
(distributed.communication.p2p) over the pp group.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from ..... import distributed as dist


class SendRecvMeta:
    """Records activation shapes/dtypes exchanged between stages. The
    reference sends this over the wire once (p2p_cache_shape); here shapes
    are static under jit, so it is pure bookkeeping."""

    def __init__(self):
        self.send_shape_message: Optional[Tuple] = None
        self.send_dtype_message: Optional[Tuple] = None
        self.recv_shape_message: Optional[Tuple] = None
        self.recv_dtype_message: Optional[Tuple] = None
        self.has_send_meta = False
        self.has_recv_meta = False

    def set_send_message(self, tensor_or_tuple):
        ts = (tensor_or_tuple if isinstance(tensor_or_tuple, (tuple, list))
              else (tensor_or_tuple,))
        self.send_shape_message = tuple(tuple(t.shape) for t in ts)
        self.send_dtype_message = tuple(str(t.dtype) for t in ts)
        self.has_send_meta = True

    def recv_meta(self, group=None):
        # static shapes: the handshake is a no-op; mirror send → recv
        self.recv_shape_message = self.send_shape_message
        self.recv_dtype_message = self.send_dtype_message
        self.has_recv_meta = self.has_send_meta

    def send_meta(self, tensor_or_tuple, group=None):
        self.set_send_message(tensor_or_tuple)


def _pp_group(hcg):
    return hcg.get_pipe_parallel_group() if hcg is not None else None


def send_forward(output_tensor, pp_last_stage: bool, hcg=None):
    if pp_last_stage:
        return None
    g = _pp_group(hcg)
    nxt = (g.rank + 1) % g.nranks if g else 1
    return dist.send(output_tensor, dst=nxt, group=g)


def recv_forward(pp_first_stage: bool, ref_tensor=None, hcg=None):
    if pp_first_stage:
        return None
    g = _pp_group(hcg)
    prev = (g.rank - 1) % g.nranks if g else 0
    return dist.recv(ref_tensor, src=prev, group=g)


def send_backward(input_tensor_grad, pp_first_stage: bool, hcg=None):
    if pp_first_stage:
        return None
    g = _pp_group(hcg)
    prev = (g.rank - 1) % g.nranks if g else 0
    return dist.send(input_tensor_grad, dst=prev, group=g)


def recv_backward(pp_last_stage: bool, ref_tensor=None, hcg=None):
    if pp_last_stage:
        return None
    g = _pp_group(hcg)
    nxt = (g.rank + 1) % g.nranks if g else 1
    return dist.recv(ref_tensor, src=nxt, group=g)
