"""p2p_communication — stage-to-stage transfer API parity.

Reference: .../meta_parallel/pp_utils/p2p_communication.py: NCCL send/recv
pairs with a ``SendRecvMeta`` shape/dtype handshake (the receiver must
allocate before NCCL recv), batched isend/irecv.

On TPU the production path does NOT use these: the pipelined train step is
one SPMD program whose stage shift is an XLA collective-permute (see
pipeline_parallel.py), so shapes are static and no handshake exists. This
module keeps the reference surface for user code/tests that drive p2p
manually — each call forwards to the eager collective facade
(distributed.communication.p2p) over the pp group.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from ..... import distributed as dist


class SendRecvMeta:
    """Records activation shapes/dtypes exchanged between stages. The
    reference sends this over the wire once (p2p_cache_shape); here shapes
    are static under jit, so it is pure bookkeeping."""

    def __init__(self):
        self.send_shape_message: Optional[Tuple] = None
        self.send_dtype_message: Optional[Tuple] = None
        self.recv_shape_message: Optional[Tuple] = None
        self.recv_dtype_message: Optional[Tuple] = None
        self.has_send_meta = False
        self.has_recv_meta = False

    def set_send_message(self, tensor_or_tuple):
        ts = (tensor_or_tuple if isinstance(tensor_or_tuple, (tuple, list))
              else (tensor_or_tuple,))
        self.send_shape_message = tuple(tuple(t.shape) for t in ts)
        self.send_dtype_message = tuple(str(t.dtype) for t in ts)
        self.has_send_meta = True

    def recv_meta(self, group=None):
        # static shapes: the handshake is a no-op; mirror send → recv
        self.recv_shape_message = self.send_shape_message
        self.recv_dtype_message = self.send_dtype_message
        self.has_recv_meta = self.has_send_meta

    def send_meta(self, tensor_or_tuple, group=None):
        self.set_send_message(tensor_or_tuple)


def _pp_group(hcg):
    if hcg is None:
        from ...base_topology import try_get_hybrid_communicate_group
        hcg = try_get_hybrid_communicate_group()
    return (hcg.get_pipe_parallel_group() if hcg is not None
            else None), hcg


def _stage_and_world(hcg):
    """(this stage's id, pp world size).  The stage id comes from the
    TOPOLOGY (the hcg's pipe coordinate == its rank within the cached pp
    group), never from process identity — both endpoints of every
    transfer below are derived from it, so a ``send_forward`` at stage s
    and the ``recv_forward`` at stage s+1 address the same mailbox key
    (src=s, dst=s+1) by construction.  Without a topology there IS no
    stage identity and no pairable key — fail loudly instead of
    stranding the peer."""
    if hcg is None:
        raise RuntimeError(
            "pp_utils p2p needs a hybrid topology to derive both "
            "endpoints of the transfer: call fleet.init(...) first or "
            "pass hcg= explicitly")
    return hcg.get_stage_id(), hcg.get_pipe_parallel_world_size()


def send_forward(output_tensor, pp_last_stage: bool = None, hcg=None):
    if pp_last_stage:           # explicit boundary no-op: no transfer,
        return None             # no stage identity or topology needed
    g, hcg = _pp_group(hcg)
    s, world = _stage_and_world(hcg)
    if pp_last_stage is None and s == world - 1:
        return None
    # stage-conditional by design (boundary stages sit out one transfer,
    # mirroring the reference API); both endpoints derive from the stage
    # id so the keys pair by construction — TestPipelineP2P drives every
    # consecutive stage pair  # meshcheck: disable=MSH004
    return dist.send(output_tensor, dst=s + 1, group=g, src=s)


def recv_forward(pp_first_stage: bool = None, ref_tensor=None, hcg=None):
    if pp_first_stage:          # explicit boundary no-op
        return None
    g, hcg = _pp_group(hcg)
    s, world = _stage_and_world(hcg)
    if pp_first_stage is None and s == 0:
        return None
    # paired with stage s-1's send_forward key (s-1, s) by construction
    # meshcheck: disable=MSH004
    return dist.recv(ref_tensor, src=s - 1, group=g, dst=s)


def send_backward(input_tensor_grad, pp_first_stage: bool = None,
                  hcg=None):
    if pp_first_stage:          # explicit boundary no-op
        return None
    g, hcg = _pp_group(hcg)
    s, world = _stage_and_world(hcg)
    if pp_first_stage is None and s == 0:
        return None
    # paired with stage s-1's recv_backward key (s, s-1) by construction
    # meshcheck: disable=MSH004
    return dist.send(input_tensor_grad, dst=s - 1, group=g, src=s)


def recv_backward(pp_last_stage: bool = None, ref_tensor=None, hcg=None):
    if pp_last_stage:           # explicit boundary no-op
        return None
    g, hcg = _pp_group(hcg)
    s, world = _stage_and_world(hcg)
    if pp_last_stage is None and s == world - 1:
        return None
    # paired with stage s+1's send_backward key (s+1, s) by construction
    # meshcheck: disable=MSH004
    return dist.recv(ref_tensor, src=s + 1, group=g, dst=s)
