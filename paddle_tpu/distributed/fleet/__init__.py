"""paddle_tpu.distributed.fleet — distributed runtime facade
(reference: python/paddle/distributed/fleet/).

Grows through the build: topology + RNG now; fleet.init/distributed_model/
meta_parallel wrappers as milestones land.
"""

from .utils.fs import HDFSClient, LocalFS, UtilBase  # noqa: F401
from . import base_topology, layers, meta_optimizers, meta_parallel, random, utils  # noqa: F401
from .base_topology import (  # noqa: F401
    CommGroup, CommunicateTopology, HybridCommunicateGroup,
    create_hybrid_communicate_group, get_hybrid_communicate_group,
)
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet import (  # noqa: F401
    Fleet, distributed_model, distributed_optimizer, init, is_initialized,
)
from .meta_optimizers import (  # noqa: F401
    DygraphShardingOptimizer, HybridParallelGradScaler, HybridParallelOptimizer,
)
from .meta_parallel import (  # noqa: F401
    ColumnParallelLinear, GroupShardedOptimizerStage2, GroupShardedStage2,
    GroupShardedStage3, LayerDesc, ParallelCrossEntropy, parallel_matmul, PipelineLayer,
    PipelineParallel, RowParallelLinear, SharedLayerDesc,
    VocabParallelEmbedding,
)
from .random import RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed  # noqa: F401
from .role_maker import (  # noqa: F401
    PaddleCloudRoleMaker, Role, UserDefinedRoleMaker,
)


def _bind_fleet_method(name):
    def call(*a, **k):
        from .fleet import _fleet_singleton   # late-bound singleton
        return getattr(_fleet_singleton, name)(*a, **k)
    call.__name__ = name
    return call


for _n in ("worker_num", "worker_index", "is_worker", "is_server",
           "is_first_worker", "worker_endpoints", "server_num",
           "server_index", "server_endpoints", "init_worker",
           "init_server", "run_server", "stop_worker", "barrier_worker"):
    globals()[_n] = _bind_fleet_method(_n)
del _n
