"""Hybrid-parallel topology over a TPU device mesh.

Reference: python/paddle/distributed/fleet/base/topology.py
(``CommunicateTopology``, ``HybridCommunicateGroup``). The reference lays
processes on a rank grid ordered [dp, pp, sharding, sep, mp] — mp innermost so
TP traffic rides NVLink. Here the grid IS a ``jax.sharding.Mesh``: mp maps to
the innermost ICI axis, dp outermost (DCN when multi-host). A "process group"
becomes a mesh axis name; collectives over it are XLA collectives inside
jitted/shard_mapped programs.

Axis name mapping (reference degree -> mesh axis):
  dp_degree       -> "dp"     (data parallel)
  pp_degree       -> "pp"     (pipeline stages)
  sharding_degree -> "sharding" (ZeRO; usually fused with dp on TPU)
  sep_degree      -> "sep"    (sequence/context parallel: Ulysses/ring)
  mp_degree       -> "mp"     (tensor parallel, innermost)
"""

from __future__ import annotations

import collections
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

_HYBRID_AXES = ("dp", "pp", "sharding", "sep", "mp")

_CURRENT_HCG: Optional["HybridCommunicateGroup"] = None


class CommGroup:
    """Facade for a communication group: a (mesh, axis) pair.

    Stands in for the reference's ProcessGroup handle returned by
    ``new_group``/HCG getters. ``axis_name`` is what collective ops use inside
    shard_map; ``ranks`` reflect the logical rank grid.
    """

    def __init__(self, mesh: Optional[Mesh], axis_name: Optional[str],
                 ranks: List[int], rank: int):
        # deterministic identity: two CommGroups over the same axis and
        # member set ARE the same logical group, whichever HCG instance
        # built them — the eager p2p mailbox keys transfers by group id,
        # so a per-instance counter would strand every send whose recv
        # came through a different (but identical) group object
        self.id = f"{axis_name or 'world'}:" + ",".join(
            str(int(r)) for r in ranks)
        self.mesh = mesh
        self.axis_name = axis_name
        self.ranks = list(ranks)
        self.rank = rank          # this process's rank within the group, or -1
        self.nranks = len(ranks)

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def name(self) -> str:
        return f"comm_group_{self.id}_{self.axis_name or 'world'}"

    def get_group_rank(self, global_rank: int) -> int:
        return self.ranks.index(global_rank) if global_rank in self.ranks else -1

    def process_group(self):
        return self

    def __repr__(self):
        return f"CommGroup(axis={self.axis_name}, ranks={self.ranks}, rank={self.rank})"


class CommunicateTopology:
    """The rank grid (reference class of the same name)."""

    def __init__(
        self,
        hybrid_group_names: Sequence[str] = ("data", "pipe", "sharding", "sep", "model"),
        dims: Sequence[int] = (1, 1, 1, 1, 1),
    ):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(int(d) for d in dims)
        self.coordinate = collections.namedtuple("Coordinate", self._parallel_names)
        self._world_size = int(np.prod(self._dims))
        ranks = range(self._world_size)
        self._coord2rank = dict(zip(
            (self.coordinate(*c) for c in itertools.product(*(range(d) for d in self._dims))),
            ranks))
        self._rank2coord = {v: k for k, v in self._coord2rank.items()}

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world_size

    def get_rank(self, **args) -> int:
        return self._coord2rank[self.coordinate(**args)]

    def get_coord(self, rank: int):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items() if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """All groups along ``axis_name``: one list of ranks per combination of
        the other axes (the reference's group-building enumeration)."""
        axis = self._parallel_names.index(axis_name)
        other_dims = [range(d) for i, d in enumerate(self._dims) if i != axis]
        out = []
        for combo in itertools.product(*other_dims):
            ranks = []
            for i in range(self._dims[axis]):
                coord = list(combo)
                coord.insert(axis, i)
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            out.append(ranks)
        return out

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = self.get_coord(global_rank)
        tf = coord._replace(**kwargs)._asdict()
        return self.get_rank(**tf)


class HybridCommunicateGroup:
    """Reference-shaped facade over the device mesh.

    Build from degrees; exposes the reference's getters plus ``get_mesh()``
    for the jit/GSPMD path. On a single controller, the "current rank" is
    process-based (multi-host: jax.process_index spans the dp/pp outer axes).
    """

    def __init__(self, topology: CommunicateTopology,
                 mesh: Optional[Mesh] = None, global_rank: int = 0):
        self._topo = topology
        names = topology.get_hybrid_group_names()
        deg = {n: topology.get_dim(n) for n in names}
        self._dp_degree = deg.get("data", 1)
        self._pp_degree = deg.get("pipe", 1)
        self._sharding_degree = deg.get("sharding", 1)
        self._sep_degree = deg.get("sep", 1)
        self._mp_degree = deg.get("model", 1)
        self.nranks = topology.world_size()
        self.global_rank = global_rank
        self._mesh = mesh if mesh is not None else self._build_mesh()
        self._axis_groups: Dict[str, CommGroup] = {}

        coord = self._topo.get_coord(global_rank)
        self._dp_rank = coord.data if hasattr(coord, "data") else 0
        self._pp_rank = coord.pipe if hasattr(coord, "pipe") else 0
        self._sharding_rank = coord.sharding if hasattr(coord, "sharding") else 0
        self._sep_rank = coord.sep if hasattr(coord, "sep") else 0
        self._mp_rank = coord.model if hasattr(coord, "model") else 0

        global _CURRENT_HCG
        _CURRENT_HCG = self

    def _build_mesh(self) -> Mesh:
        devices = jax.devices()
        need = self.nranks
        if len(devices) < need:
            raise RuntimeError(
                f"hybrid topology needs {need} devices, found {len(devices)}. "
                "For CPU simulation set XLA_FLAGS=--xla_force_host_platform_device_count=N.")
        grid = np.array(devices[:need]).reshape(
            self._dp_degree, self._pp_degree, self._sharding_degree,
            self._sep_degree, self._mp_degree)
        return Mesh(grid, axis_names=_HYBRID_AXES)

    # ----------------------------------------------------------------- mesh
    def get_mesh(self) -> Mesh:
        return self._mesh

    def topology(self) -> CommunicateTopology:
        return self._topo

    def get_parallel_mode(self) -> str:
        # mirrors reference ParallelMode decision
        if self._mp_degree == 1 and self._pp_degree == 1 and self._sharding_degree == 1:
            return "DATA_PARALLEL" if self._dp_degree > 1 else "SINGLE"
        if self._mp_degree > 1 and self._pp_degree == 1:
            return "TENSOR_PARALLEL"
        if self._pp_degree > 1:
            return "PIPELINE_PARALLEL"
        return "SHARDING_PARALLEL"

    def _axis_group(self, axis: str, rank_in_axis: int) -> CommGroup:
        cached = self._axis_groups.get(axis)
        if cached is not None:
            return cached
        name_map = {"dp": "data", "pp": "pipe", "sharding": "sharding",
                    "sep": "sep", "mp": "model"}
        comm_lists = self._topo.get_comm_list(name_map[axis])
        my = next((g for g in comm_lists if self.global_rank in g), comm_lists[0])
        grp = CommGroup(self._mesh, axis, my, my.index(self.global_rank)
                        if self.global_rank in my else 0)
        self._axis_groups[axis] = grp
        return grp

    # --------------------------------------------------------------- global
    def get_global_rank(self) -> int:
        return self.global_rank

    # ------------------------------------------------------------------- dp
    def get_data_parallel_rank(self) -> int:
        return self._dp_rank

    def get_data_parallel_world_size(self) -> int:
        return self._dp_degree

    def get_data_parallel_group(self) -> CommGroup:
        return self._axis_group("dp", self._dp_rank)

    def get_data_parallel_group_src_rank(self) -> int:
        return self.get_data_parallel_group().ranks[0]

    # ------------------------------------------------------------------- mp
    def get_model_parallel_rank(self) -> int:
        return self._mp_rank

    def get_model_parallel_world_size(self) -> int:
        return self._mp_degree

    def get_model_parallel_group(self) -> CommGroup:
        return self._axis_group("mp", self._mp_rank)

    def get_model_parallel_group_src_rank(self) -> int:
        return self.get_model_parallel_group().ranks[0]

    # ------------------------------------------------------------------- pp
    def get_stage_id(self) -> int:
        return self._pp_rank

    def get_pipe_parallel_world_size(self) -> int:
        return self._pp_degree

    def get_pipe_parallel_group(self) -> CommGroup:
        return self._axis_group("pp", self._pp_rank)

    def is_first_stage(self) -> bool:
        return self._pp_rank == 0

    def is_last_stage(self) -> bool:
        return self._pp_rank == self._pp_degree - 1

    def get_p2p_groups(self):
        return None  # p2p rides ppermute inside the jitted pipeline schedule

    # -------------------------------------------------------------- sharding
    def get_sharding_parallel_rank(self) -> int:
        return self._sharding_rank

    def get_sharding_parallel_world_size(self) -> int:
        return self._sharding_degree

    def get_sharding_parallel_group(self) -> CommGroup:
        return self._axis_group("sharding", self._sharding_rank)

    def get_sharding_parallel_group_src_rank(self) -> int:
        return self.get_sharding_parallel_group().ranks[0]

    # ------------------------------------------------------------------ sep
    def get_sep_parallel_rank(self) -> int:
        return self._sep_rank

    def get_sep_parallel_world_size(self) -> int:
        return self._sep_degree

    def get_sep_parallel_group(self) -> CommGroup:
        return self._axis_group("sep", self._sep_rank)

    # ------------------------------------------------------- combined groups
    def get_check_parallel_group(self, sharding: bool = False) -> CommGroup:
        return CommGroup(self._mesh, None, list(range(self.nranks)), self.global_rank)

    def get_rank_from_stage(self, stage_id: int, **kwargs) -> int:
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage_id, **kwargs)

    def __repr__(self):
        return (f"HybridCommunicateGroup(dp={self._dp_degree}, pp={self._pp_degree}, "
                f"sharding={self._sharding_degree}, sep={self._sep_degree}, "
                f"mp={self._mp_degree})")


def create_hybrid_communicate_group(
    dp_degree: int = 1, mp_degree: int = 1, pp_degree: int = 1,
    sharding_degree: int = 1, sep_degree: int = 1,
) -> HybridCommunicateGroup:
    topo = CommunicateTopology(
        ("data", "pipe", "sharding", "sep", "model"),
        (dp_degree, pp_degree, sharding_degree, sep_degree, mp_degree))
    return HybridCommunicateGroup(topo)


def try_get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _CURRENT_HCG


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    if _CURRENT_HCG is None:
        raise RuntimeError("fleet.init(...) has not been called")
    return _CURRENT_HCG


def _reset_hcg():
    global _CURRENT_HCG
    _CURRENT_HCG = None
    # deterministic CommGroup ids mean a rebuilt topology re-derives the
    # SAME mailbox keys — drain undelivered p2p sends so a stale tensor
    # from a torn-down run can never be delivered into the next one
    from ..communication import p2p
    p2p._MAILBOX.clear()
