"""Dygraph meta-optimizers (reference:
python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/)."""

from .dygraph_sharding_optimizer import DygraphShardingOptimizer  # noqa: F401
from .hybrid_parallel_optimizer import (  # noqa: F401
    HybridParallelGradScaler, HybridParallelOptimizer,
)
