"""Stage-1 sharding optimizer (reference:
python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
dygraph_sharding_optimizer.py — DygraphShardingOptimizer: partitions the
parameter list across the sharding group, each rank runs the inner optimizer
on its slice, then broadcasts updated params).

TPU: the partition is a sharding declaration on the optimizer-state tree;
GSPMD reduce-scatters grads to the owning shard, updates locally, and
all-gathers updated params — the same traffic the reference hand-codes.
"""

from __future__ import annotations

from ..base_topology import try_get_hybrid_communicate_group
from ..meta_parallel.sharding.group_sharded_utils import resolve_sharding_axis


class DygraphShardingOptimizer:
    def __init__(self, optimizer=None, hcg=None, user_defined_strategy=None,
                 params=None, inner_optimizer_class=None, **inner_kw):
        # reference signature historically took (hcg, user_defined_strategy,
        # params, inner_optimizer_class, **kw); newer trees take (optimizer,
        # hcg). Accept both.
        if optimizer is None and inner_optimizer_class is not None:
            optimizer = inner_optimizer_class(parameters=params, **inner_kw)
        self._inner_opt = optimizer
        self._hcg = hcg or try_get_hybrid_communicate_group()
        axis = "sharding"
        if self._hcg is not None:
            ax = resolve_sharding_axis(self._hcg.get_mesh())
            if ax is not None:
                axis = ax
        optimizer._group_sharded_level = max(
            getattr(optimizer, "_group_sharded_level", 0), 1)
        optimizer._sharding_axis = axis

    def __getattr__(self, item):
        try:
            return getattr(self.__dict__["_inner_opt"], item)
        except KeyError:
            raise AttributeError(item) from None

    def step(self):
        return self._inner_opt.step()

    def clear_grad(self, *a, **k):
        return self._inner_opt.clear_grad(*a, **k)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
