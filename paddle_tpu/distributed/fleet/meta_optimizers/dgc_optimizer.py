"""DGC — Deep Gradient Compression momentum (reference:
python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py wrapping
the DGCMomentumOptimizer + paddle/fluid/operators/dgc_op; algorithm from
Lin et al., "Deep Gradient Compression", ICLR 2018).

TPU-native collapse: the reference's point is sending the top-k gradient
entries over NCCL. Under GSPMD the partitioner owns the collectives and
the all-reduce stays dense, so what survives — and what this class
implements exactly — is DGC's *algorithmic* core as one jit transform of
the update rule:

  - momentum correction:   u_t = m·u_{t-1} + g_t
  - error accumulation:    v_t = v_{t-1} + u_t
  - top-k sparsification:  mask = |v_t| ≥ τ(s),  update = v_t·mask
  - error feedback:        v_{t+1} = v_t·(1-mask)
  - momentum factor masking: u_{t+1} = u_t·(1-mask)
  - sparsity rampup:       s steps through ``sparsity`` every
                           ``rampup_step`` steps after
                           ``rampup_begin_step`` (plain momentum before)

τ is estimated from a strided sample of |v| (the paper's own 0.1%
sampling trick — an exact top-k on a 100M-param tensor would dominate
the step).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from ....optimizer.optimizer import Momentum

__all__ = ["DGCMomentum"]

_SAMPLE = 4096


class DGCMomentum(Momentum):
    def __init__(self, learning_rate=0.001, momentum=0.9,
                 rampup_begin_step: int = 0, rampup_step: int = 1,
                 sparsity: Sequence[float] = (0.999,), parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 num_trainers: Optional[int] = None,
                 multi_precision: bool = False, name=None):
        if use_nesterov:
            # DGC's momentum correction is defined for plain momentum
            # (Lin et al. §3); silently switching Nesterov off at rampup
            # would be a hidden optimizer change — reject up front
            raise NotImplementedError(
                "DGCMomentum does not support use_nesterov=True (the "
                "sparsified momentum-correction update is plain "
                "momentum); use Momentum without strategy.dgc")
        super().__init__(learning_rate, momentum, parameters, use_nesterov,
                         weight_decay, grad_clip, multi_precision, name)
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._sparsity = tuple(float(s) for s in sparsity) or (0.999,)

    def init_slot(self, p_val):
        return {"velocity": jnp.zeros_like(p_val, dtype=jnp.float32),
                "error": jnp.zeros_like(p_val, dtype=jnp.float32)}

    def _current_sparsity(self, t):
        """Rampup: chunk i of ``rampup_step``/len(sparsity) steps uses
        sparsity[i] (the reference's schedule shape)."""
        levels = jnp.asarray(self._sparsity, jnp.float32)
        per = max(1, self._rampup_step // len(self._sparsity))
        idx = jnp.clip((t - self._rampup_begin) // per,
                       0, len(self._sparsity) - 1)
        return levels[idx.astype(jnp.int32)]

    def apply_one(self, p, g, slots, lr, t, wd):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * p32

        u, v = slots["velocity"], slots["error"]

        # dense momentum branch (pre-rampup) — matches Momentum exactly
        dense_u = self._momentum * u + g32
        dense_upd = (g32 + self._momentum * dense_u if self._nesterov
                     else dense_u)

        # DGC branch
        u2 = self._momentum * u + g32
        acc = v + u2
        flat = jnp.abs(acc).reshape(-1)
        stride = max(1, flat.shape[0] // _SAMPLE)
        sample = flat[::stride][:_SAMPLE]
        s = self._current_sparsity(t)
        tau = jnp.quantile(sample, jnp.clip(s, 0.0, 1.0))
        mask = (jnp.abs(acc) >= tau).astype(jnp.float32)
        sparse_upd = acc * mask
        dgc_u = u2 * (1.0 - mask)
        dgc_v = acc * (1.0 - mask)

        use_dgc = t >= self._rampup_begin
        upd = jnp.where(use_dgc, sparse_upd, dense_upd)
        new_u = jnp.where(use_dgc, dgc_u, dense_u)
        new_v = jnp.where(use_dgc, dgc_v, v)
        new_p = (p32 - lr * upd).astype(p.dtype)
        return new_p, {"velocity": new_u, "error": new_v}
