"""HybridParallelOptimizer / HybridParallelGradScaler (reference:
python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py).

The reference's job is cross-group bookkeeping: allreduce the grad-norm
across mp/pp/sharding groups before global clipping, sync mp-duplicated
grads, scale by dp degree. Under the single-controller GSPMD model every
gradient the optimizer sees is the LOGICAL full gradient (XLA already summed
partials across groups), so global-norm clip over the grad tree is global by
construction — the wrapper only preserves the reference API and routes
stage-1 sharding declarations.
"""

from __future__ import annotations

from ....amp.grad_scaler import GradScaler
from ..base_topology import try_get_hybrid_communicate_group
from .dygraph_sharding_optimizer import DygraphShardingOptimizer


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._hcg = hcg or try_get_hybrid_communicate_group()
        self._strategy = strategy
        sharding_degree = (
            self._hcg.get_sharding_parallel_world_size()
            if self._hcg is not None else 1)
        if sharding_degree > 1 and not isinstance(
                optimizer, DygraphShardingOptimizer):
            optimizer = DygraphShardingOptimizer(optimizer, self._hcg)
        self._inner_opt = optimizer

    def __getattr__(self, item):
        try:
            return getattr(self.__dict__["_inner_opt"], item)
        except KeyError:
            raise AttributeError(item) from None

    def step(self):
        return self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters,
                                        no_grad_set)

    def clear_grad(self, *a, **k):
        return self._inner_opt.clear_grad(*a, **k)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)


class HybridParallelGradScaler(GradScaler):
    """Reference: allreduces found_inf across the model-parallel group. The
    single-controller scaler sees the global loss, so found_inf is already
    global; this subclass exists for API parity."""

    def __init__(self, scaler=None, hcg=None, **kw):
        if isinstance(scaler, GradScaler):
            self.__dict__.update(scaler.__dict__)
        else:
            super().__init__(**kw)
        self._hcg = hcg
