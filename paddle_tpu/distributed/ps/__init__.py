"""Parameter-server runtime — the async/sparse path for embedding-heavy
(recommendation/search) workloads.

Reference: paddle/fluid/distributed/ps/{service,table}/ (the brpc-based
C++ PS: ``BrpcPsServer``, ``MemorySparseTable``, accessors) plus the
Python surface ``fleet.init_server/run_server/init_worker/stop_worker``
and the ``TRAINING_ROLE=PSERVER|TRAINER`` env protocol
(python/paddle/distributed/fleet/base/role_maker.py).

TPU-first redesign, not a port: the defining PS workload is embedding
tables far larger than accelerator memory, touched sparsely and updated
asynchronously. On a TPU pod the dense math belongs on chip under jit;
the tables belong in HOST memory next to the input pipeline. So:

* tables live in server processes as hash-sharded numpy rows
  (``id % n_servers`` picks the shard, exactly the reference's default
  sparse-table partitioner);
* workers pull rows / push grads over the job's authenticated HTTP
  control plane — the same ``X-Job-Token`` + endpoints protocol the
  launcher's KV master and ``distributed.rpc`` already use (brpc has no
  TPU-side value; the payloads here are numpy buffers, not protos);
* the optimizer runs SERVER-side per row (async-SGD ``a_sync=True``
  semantics: push applies immediately, no global barrier per step);
* pulled rows enter the jitted dense path as ordinary arrays;
  :class:`DistributedEmbedding` pushes row grads at backward time via
  PyLayer, outside jit — host lookup stays off the compiled hot path.
"""

from __future__ import annotations

import os
import pickle
import threading
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = [
    "SparseTable", "DenseTable", "PSServer", "PSClient",
    "DistributedEmbedding", "the_client", "set_client",
]


# ================================================================= tables
def _make_rows(ids: np.ndarray, dim: int, init: str, scale: float,
               seed: int) -> np.ndarray:
    """Deterministic per-id init: every server (and any re-created shard)
    materializes the same row for the same id — the reference gets this
    from its accessor's per-feature init; here a per-id seeded RNG."""
    out = np.empty((len(ids), dim), np.float32)
    if init == "zeros":
        out[:] = 0.0
        return out
    for j, i in enumerate(ids):
        rng = np.random.default_rng([seed, int(i)])
        out[j] = rng.uniform(-scale, scale, dim).astype(np.float32)
    return out


class SparseTable:
    """Hash-map id -> f32 row, with the optimizer applied server-side on
    push (reference: MemorySparseTable + sparse accessors; SGD/Adagrad/
    Adam mirror the reference's naive/adagrad/adam sparse value names)."""

    def __init__(self, dim: int, optimizer: str = "sgd", lr: float = 0.05,
                 initializer: str = "uniform", init_scale: float = 0.01,
                 seed: int = 0, beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8):
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unsupported sparse optimizer {optimizer!r}")
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.initializer = initializer
        self.init_scale = float(init_scale)
        self.seed = int(seed)
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self._rows: Dict[int, np.ndarray] = {}
        self._slots: Dict[int, list] = {}      # per-id optimizer state
        self._lock = threading.Lock()

    # ------------------------------------------------------------- access
    def _ensure(self, ids: np.ndarray) -> None:
        missing = np.array([i for i in ids if int(i) not in self._rows],
                           np.int64)
        if len(missing):
            rows = _make_rows(missing, self.dim, self.initializer,
                              self.init_scale, self.seed)
            for j, i in enumerate(missing):
                self._rows[int(i)] = rows[j]

    def pull(self, ids: np.ndarray) -> np.ndarray:
        with self._lock:
            self._ensure(ids)
            return np.stack([self._rows[int(i)] for i in ids]) \
                if len(ids) else np.zeros((0, self.dim), np.float32)

    def push(self, ids: np.ndarray, grads: np.ndarray) -> None:
        """Apply the per-row update. ids may repeat — duplicates are
        summed first (one optimizer step per touched row, like the
        reference's push_sparse merge)."""
        if grads.shape != (len(ids), self.dim):
            raise ValueError(f"push grads {grads.shape} != "
                             f"({len(ids)}, {self.dim})")
        uniq, inv = np.unique(np.asarray(ids, np.int64),
                              return_inverse=True)
        acc = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(acc, inv, grads.astype(np.float32))
        with self._lock:
            self._ensure(uniq)
            for j, i in enumerate(uniq):
                self._apply(int(i), acc[j])

    def _apply(self, i: int, g: np.ndarray) -> None:
        w = self._rows[i]
        if self.optimizer == "sgd":
            w -= self.lr * g
        elif self.optimizer == "adagrad":
            g2 = self._slots.setdefault(i, [np.zeros(self.dim,
                                                     np.float32)])[0]
            g2 += g * g
            w -= self.lr * g / (np.sqrt(g2) + self.eps)
        else:                                   # adam
            m, v, t = self._slots.setdefault(
                i, [np.zeros(self.dim, np.float32),
                    np.zeros(self.dim, np.float32), 0])
            t += 1
            self._slots[i][2] = t
            m *= self.beta1
            m += (1 - self.beta1) * g
            v *= self.beta2
            v += (1 - self.beta2) * g * g
            mh = m / (1 - self.beta1 ** t)
            vh = v / (1 - self.beta2 ** t)
            w -= self.lr * mh / (np.sqrt(vh) + self.eps)

    # --------------------------------------------------------- save/load
    def state(self) -> dict:
        with self._lock:
            ids = np.array(sorted(self._rows), np.int64)
            rows = (np.stack([self._rows[int(i)] for i in ids])
                    if len(ids) else np.zeros((0, self.dim), np.float32))
            return {"ids": ids, "rows": rows}

    def load_state(self, state: dict) -> None:
        with self._lock:
            self._rows = {int(i): np.array(r, np.float32)
                          for i, r in zip(state["ids"], state["rows"])}
            self._slots.clear()                 # slots restart (reference
                                                # save formats drop them
                                                # at base save level too)

    def __len__(self) -> int:
        return len(self._rows)


class DenseTable:
    """A replicated dense parameter hosted by one server (the reference
    round-robins dense vars over servers; the client does the same)."""

    def __init__(self, shape, lr: float = 0.05, init: str = "zeros",
                 seed: int = 0):
        self.lr = float(lr)
        if init == "zeros":
            self._w = np.zeros(shape, np.float32)
        else:
            rng = np.random.default_rng(seed)
            self._w = rng.uniform(-0.01, 0.01, shape).astype(np.float32)
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self._w.copy()

    def push(self, grad: np.ndarray) -> None:
        with self._lock:
            self._w -= self.lr * grad.astype(np.float32)

    def state(self) -> dict:
        with self._lock:
            return {"w": self._w.copy()}

    def load_state(self, state: dict) -> None:
        with self._lock:
            self._w = np.array(state["w"], np.float32)


# ================================================================= server
def _check_token(handler: BaseHTTPRequestHandler,
                 token: Optional[str]) -> bool:
    from ..launch.kv_master import check_job_token
    return check_job_token(handler, token)


class _PSHandler(BaseHTTPRequestHandler):
    server_obj: "PSServer"

    def log_message(self, *a):
        pass

    def do_POST(self):
        srv = self.server_obj
        if not _check_token(self, srv.token):
            return
        n = int(self.headers.get("Content-Length", 0))
        op, payload = pickle.loads(self.rfile.read(n))
        try:
            result = (True, srv.handle(op, payload))
        except Exception as e:              # marshal to the caller
            result = (False, e)
        body = pickle.dumps(result)
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class PSServer:
    """One table-shard server (reference BrpcPsServer). Tables are
    created lazily and idempotently from client specs so servers need no
    model code at all."""

    def __init__(self, bind_ip: str = "0.0.0.0", port: int = 0,
                 token: Optional[str] = None,
                 load_dir: Optional[str] = None,
                 server_index: int = 0):
        self.token = (token if token is not None
                      else os.environ.get("PADDLE_JOB_TOKEN"))
        self.tables: Dict[int, Any] = {}
        self.load_dir = load_dir            # lazy: applied per-table on
        self.server_index = server_index    # create_table (tables exist
        self._lock = threading.Lock()       # only once a client specs them)
        handler = type("_H", (_PSHandler,), {})
        self._httpd = ThreadingHTTPServer((bind_ip, port), handler)
        handler.server_obj = self
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._done = threading.Event()

    # --------------------------------------------------------------- ops
    def handle(self, op: str, p: dict):
        if op == "create_table":
            with self._lock:
                if p["table_id"] not in self.tables:
                    kind = p["kind"]
                    kw = dict(p["spec"])
                    t = (SparseTable(**kw) if kind == "sparse"
                         else DenseTable(**kw))
                    if self.load_dir:       # init_server(dirname) resume
                        path = os.path.join(
                            self.load_dir,
                            f"shard_{self.server_index}.pkl")
                        if os.path.exists(path):
                            with open(path, "rb") as f:
                                blob = pickle.load(f)
                            state = blob.get(str(p["table_id"]))
                            if state is not None:
                                t.load_state(state)
                    self.tables[p["table_id"]] = t
            return None
        if op == "shutdown":
            self._done.set()
            threading.Thread(target=self._httpd.shutdown,
                             daemon=True).start()
            return None
        if op == "stats":
            return {tid: (len(t) if isinstance(t, SparseTable) else 1)
                    for tid, t in self.tables.items()}
        if op == "save":
            self._save(p["dirname"], p["server_index"])
            return None
        if op == "load":
            self._load(p["dirname"], p["server_index"])
            return None
        t = self.tables[p["table_id"]]
        if op == "pull_sparse":
            return t.pull(p["ids"])
        if op == "push_sparse":
            return t.push(p["ids"], p["grads"])
        if op == "pull_dense":
            return t.pull()
        if op == "push_dense":
            return t.push(p["grad"])
        raise ValueError(f"unknown PS op {op!r}")

    def _save(self, dirname: str, idx: int) -> None:
        os.makedirs(dirname, exist_ok=True)
        blob = {str(tid): t.state() for tid, t in self.tables.items()}
        with open(os.path.join(dirname, f"shard_{idx}.pkl"), "wb") as f:
            pickle.dump(blob, f)

    def _load(self, dirname: str, idx: int) -> None:
        with open(os.path.join(dirname, f"shard_{idx}.pkl"), "rb") as f:
            blob = pickle.load(f)
        for tid, state in blob.items():
            self.tables[int(tid)].load_state(state)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def run(self) -> None:
        """Blocking serve (fleet.run_server): returns after a client
        sends ``shutdown``."""
        self.start()
        self._done.wait()
        self._thread.join(timeout=10)

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=10)


# ================================================================= client
class PSClient:
    """Worker-side stub (reference BrpcPsClient): partitions sparse ids
    by ``id % n_servers``, merges duplicate ids before the wire, fans
    requests out over a thread pool, reassembles in input order."""

    def __init__(self, server_endpoints: List[str],
                 token: Optional[str] = None, timeout: float = 60.0):
        if not server_endpoints:
            raise ValueError("PSClient needs at least one server endpoint")
        self.endpoints = list(server_endpoints)
        self.token = (token if token is not None
                      else os.environ.get("PADDLE_JOB_TOKEN"))
        self.timeout = timeout
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, len(self.endpoints)))

    # --------------------------------------------------------------- rpc
    def _call(self, server: int, op: str, payload: dict):
        req = urllib.request.Request(
            f"http://{self.endpoints[server]}/", method="POST",
            data=pickle.dumps((op, payload)))
        if self.token:
            req.add_header("X-Job-Token", self.token)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            ok, result = pickle.loads(r.read())
        if not ok:
            raise result
        return result

    def _all(self, op: str, payload_fn) -> list:
        futs = [self._pool.submit(self._call, s, op, payload_fn(s))
                for s in range(len(self.endpoints))]
        return [f.result() for f in futs]

    # ------------------------------------------------------------- tables
    def create_sparse_table(self, table_id: int, dim: int, **spec) -> None:
        spec["dim"] = dim
        self._all("create_table", lambda s: {
            "table_id": table_id, "kind": "sparse", "spec": spec})

    def create_dense_table(self, table_id: int, shape, **spec) -> None:
        spec["shape"] = shape
        self._call(table_id % len(self.endpoints), "create_table", {
            "table_id": table_id, "kind": "dense", "spec": spec})

    def pull_sparse(self, table_id: int, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64).ravel()
        uniq, inv = np.unique(ids, return_inverse=True)
        n = len(self.endpoints)
        shard = uniq % n
        parts: Dict[int, np.ndarray] = {
            s: uniq[shard == s] for s in range(n) if np.any(shard == s)}
        futs = {s: self._pool.submit(self._call, s, "pull_sparse",
                                     {"table_id": table_id, "ids": part})
                for s, part in parts.items()}
        dim = None
        rows_by_id: Dict[int, np.ndarray] = {}
        for s, part in parts.items():
            rows = futs[s].result()
            dim = rows.shape[1]
            for j, i in enumerate(part):
                rows_by_id[int(i)] = rows[j]
        if dim is None:                        # empty pull
            return np.zeros((0, 0), np.float32)
        uniq_rows = np.stack([rows_by_id[int(i)] for i in uniq])
        return uniq_rows[inv]

    def push_sparse(self, table_id: int, ids: np.ndarray,
                    grads: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        uniq, inv = np.unique(ids, return_inverse=True)
        acc = np.zeros((len(uniq), grads.shape[1]), np.float32)
        np.add.at(acc, inv, grads)
        n = len(self.endpoints)
        shard = uniq % n
        futs = []
        for s in range(n):
            m = shard == s
            if np.any(m):
                futs.append(self._pool.submit(
                    self._call, s, "push_sparse",
                    {"table_id": table_id, "ids": uniq[m],
                     "grads": acc[m]}))
        for f in futs:
            f.result()

    def pull_dense(self, table_id: int) -> np.ndarray:
        return self._call(table_id % len(self.endpoints), "pull_dense",
                          {"table_id": table_id})

    def push_dense(self, table_id: int, grad: np.ndarray) -> None:
        self._call(table_id % len(self.endpoints), "push_dense",
                   {"table_id": table_id, "grad": np.asarray(grad)})

    # ---------------------------------------------------------- lifecycle
    def save(self, dirname: str) -> None:
        self._all("save", lambda s: {"dirname": dirname,
                                     "server_index": s})

    def load(self, dirname: str) -> None:
        self._all("load", lambda s: {"dirname": dirname,
                                     "server_index": s})

    def stats(self) -> list:
        return self._all("stats", lambda s: {})

    def shutdown_servers(self) -> None:
        for s in range(len(self.endpoints)):
            try:
                self._call(s, "shutdown", {})
            except OSError:
                pass                           # already gone


# ===================================================== module-level client
_client: Optional[PSClient] = None
_next_table_id = [0]


def set_client(client: Optional[PSClient]) -> None:
    global _client
    _client = client


def the_client() -> PSClient:
    if _client is None:
        raise RuntimeError(
            "no PS client: call fleet.init with TRAINING_ROLE=TRAINER + "
            "PADDLE_PSERVERS_IP_PORT_LIST set, then fleet.init_worker()")
    return _client


def _auto_table_id() -> int:
    _next_table_id[0] += 1
    return 1000 + _next_table_id[0]


# ========================================================== user surface
class DistributedEmbedding:
    """Embedding whose table lives on the parameter servers (reference:
    ``paddle.static.nn.sparse_embedding`` over a distributed lookup
    table). Forward pulls rows on host and enters the (possibly jitted
    downstream) dense path; backward pushes row grads — the server
    applies its own optimizer, so the worker optimizer never sees the
    table. Instantiate AFTER fleet.init_worker().
    """

    def __new__(cls, *args, **kwargs):          # defer heavy imports
        import paddle_tpu  # noqa: F401
        return super().__new__(cls)

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 table_id: Optional[int] = None, client: Optional[PSClient]
                 = None, optimizer: str = "sgd", lr: float = 0.05,
                 initializer: str = "uniform", init_scale: float = 0.01,
                 seed: int = 0):
        from paddle_tpu.autograd import PyLayer
        import paddle_tpu as paddle

        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.table_id = _auto_table_id() if table_id is None else table_id
        self._client = client or the_client()
        self._client.create_sparse_table(
            self.table_id, embedding_dim, optimizer=optimizer, lr=lr,
            initializer=initializer, init_scale=init_scale, seed=seed)
        # PyLayer only records a node when a differentiable input flows
        # in; ids are ints, so a zero anchor rides along (and backward
        # returns a zero grad for it)
        self._anchor = paddle.to_tensor(
            np.zeros((1,), np.float32), stop_gradient=False)
        client_ref, table_id_ref, dim = (self._client, self.table_id,
                                         embedding_dim)

        class _Lookup(PyLayer):
            @staticmethod
            def forward(ctx, anchor, ids_np):
                rows = client_ref.pull_sparse(table_id_ref, ids_np)
                ctx.ids_np = ids_np
                out = rows.reshape(ids_np.shape + (dim,))
                return paddle.to_tensor(out) + anchor * 0.0

            @staticmethod
            def backward(ctx, grad_out):
                g = grad_out.numpy().reshape(-1, dim)
                client_ref.push_sparse(table_id_ref, ctx.ids_np, g)
                return paddle.to_tensor(np.zeros((1,), np.float32))

        self._lookup = _Lookup

    def __call__(self, ids):
        ids_np = np.asarray(
            ids.numpy() if hasattr(ids, "numpy") else ids, np.int64)
        return self._lookup.apply(self._anchor, ids_np)
