"""auto_parallel: ProcessMesh + placement-annotated tensors + Engine.

Reference: python/paddle/distributed/auto_parallel/ — ``ProcessMesh``,
``shard_tensor``, ``Shard/Replicate/Partial`` placements, ``reshard``,
``dtensor_from_fn``, and the static ``Engine`` (SURVEY.md §1 L5b).

TPU-native design: this is the subsystem SURVEY §7.1 calls "nearly 1:1 with
pjit/GSPMD". A ``ProcessMesh`` is a ``jax.sharding.Mesh``; a placements list
(one entry per MESH dim saying which tensor dim it shards) converts to a
``PartitionSpec`` (one entry per TENSOR dim listing mesh axes); and
``shard_tensor``/``reshard`` are ``jax.device_put`` with the resulting
``NamedSharding``. The reference's SPMD completion pass (filling in dist
attrs on every intermediate op) is exactly what GSPMD does inside XLA, so
annotating inputs + params is the whole user-facing job. ``Partial`` is an
annotation-only state here (GSPMD materializes partial values only inside
compiled programs; a user-held partial tensor is represented replicated with
the pending-reduce recorded in ``dist_attr``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
    "shard_tensor", "dtensor_from_fn", "reshard", "shard_optimizer",
    "shard_layer", "to_static",
    "Engine", "placements_to_spec", "spec_to_placements",
]


# ------------------------------------------------------------- placements
class Placement:
    def is_shard(self, dim: Optional[int] = None) -> bool:
        return False

    def is_replicate(self) -> bool:
        return False

    def is_partial(self) -> bool:
        return False


@dataclasses.dataclass(frozen=True)
class Shard(Placement):
    """This mesh dim shards tensor dim ``dim`` (reference: dist.Shard)."""
    dim: int

    def is_shard(self, dim: Optional[int] = None) -> bool:
        return dim is None or dim == self.dim


@dataclasses.dataclass(frozen=True)
class Replicate(Placement):
    def is_replicate(self) -> bool:
        return True


@dataclasses.dataclass(frozen=True)
class Partial(Placement):
    """Pending reduction over this mesh dim (reference: dist.Partial)."""
    reduce_type: str = "sum"

    def is_partial(self) -> bool:
        return True


# ------------------------------------------------------------ ProcessMesh
class ProcessMesh:
    """An N-D logical processor array (reference:
    python/paddle/distributed/auto_parallel/process_mesh.py). ``mesh`` is a
    (nested) list / ndarray of global process ids; ``dim_names`` label the
    axes ("dp"/"mp"/"pp"/...)."""

    def __init__(self, mesh: Union[Sequence, np.ndarray],
                 dim_names: Optional[Sequence[str]] = None,
                 shape=None, process_ids=None):
        if mesh is None and shape is not None and process_ids is not None:
            arr = np.asarray(process_ids).reshape(shape)
        else:
            arr = np.asarray(mesh)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        self._mesh = arr.astype(np.int64)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(self._mesh.ndim)]
        if len(dim_names) != self._mesh.ndim:
            raise ValueError(
                f"{len(dim_names)} dim_names for a {self._mesh.ndim}-d mesh")
        self._dim_names = list(dim_names)

    # reference-shaped accessors
    @property
    def shape(self) -> List[int]:
        return list(self._mesh.shape)

    @property
    def ndim(self) -> int:
        return self._mesh.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return self._mesh.ravel().tolist()

    @property
    def mesh(self) -> np.ndarray:
        return self._mesh

    def get_dim_size(self, name: str) -> int:
        return self._mesh.shape[self._dim_names.index(name)]

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._mesh, other._mesh)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._mesh.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, "
                f"dim_names={self._dim_names})")

    # ---- jax bridge
    def to_jax_mesh(self) -> Mesh:
        """Device mesh with this topology: process id i -> jax.devices()[i]."""
        devs = jax.devices()
        n = self._mesh.size
        if n > len(devs):
            raise RuntimeError(
                f"ProcessMesh needs {n} devices, have {len(devs)}")
        arr = np.empty(self._mesh.shape, dtype=object)
        flat_ids = self._mesh.ravel()
        flat = [devs[int(i)] for i in flat_ids]
        arr.ravel()[:] = flat
        return Mesh(arr, tuple(self._dim_names))


def placements_to_spec(placements: Sequence[Placement],
                       mesh: ProcessMesh) -> P:
    """Per-mesh-dim placements -> per-tensor-dim PartitionSpec."""
    entries: dict = {}
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            entries.setdefault(pl.dim, []).append(
                mesh.dim_names[mesh_dim])
        elif not isinstance(pl, (Replicate, Partial)):
            raise TypeError(f"unknown placement {pl!r}")
    if not entries:
        return P()
    ndim = max(entries) + 1
    out = []
    for d in range(ndim):
        names = entries.get(d)
        if not names:
            out.append(None)
        elif len(names) == 1:
            out.append(names[0])
        else:
            out.append(tuple(names))
    return P(*out)


def spec_to_placements(spec: P, mesh: ProcessMesh) -> List[Placement]:
    """Inverse of placements_to_spec (Replicate for unused mesh dims)."""
    out: List[Placement] = [Replicate() for _ in range(mesh.ndim)]
    for tensor_dim, entry in enumerate(spec):
        if entry is None:
            continue
        for name in (entry,) if isinstance(entry, str) else entry:
            out[mesh.dim_names.index(name)] = Shard(tensor_dim)
    return out


# --------------------------------------------------------------- dist API
def _ensure_tensor(x, dtype=None, stop_gradient=None):
    from ...core.tensor import Tensor
    if isinstance(x, Tensor):
        return x
    t = Tensor(jnp.asarray(x), stop_gradient=True if stop_gradient is None
               else stop_gradient)
    return t


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None):
    """Distribute ``data`` over ``mesh`` per ``placements`` (reference:
    dist.shard_tensor). The value lands sharded on the devices via GSPMD
    layout; ``dist_attr``/``process_mesh``/``placements`` are recorded on
    the Tensor so parallel wrappers and TrainStep pick the spec up."""
    from ...core.tensor import Tensor

    t = _ensure_tensor(data, dtype=dtype, stop_gradient=stop_gradient)
    spec = placements_to_spec(placements, mesh)
    jmesh = mesh.to_jax_mesh()
    val = t._value
    if dtype is not None:
        from ...core.dtype import to_jax_dtype
        val = val.astype(to_jax_dtype(dtype))
    sharded = jax.device_put(val, NamedSharding(jmesh, spec))
    out = Tensor(sharded, stop_gradient=t.stop_gradient
                 if stop_gradient is None else stop_gradient)
    out.dist_attr = spec
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def dtensor_from_fn(fn, mesh: ProcessMesh, placements: Sequence[Placement],
                    *args, **kwargs):
    """Build via ``fn`` then distribute (reference: dist.dtensor_from_fn)."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x, mesh: ProcessMesh, placements: Sequence[Placement]):
    """Re-distribute an existing (dist) tensor (reference: dist.reshard).

    Partial semantics (global view): a Partial tensor stores the GLOBAL
    total — per-device partial contributions never exist at the eager
    user level (XLA inserts the actual psum/reduce-scatter when the
    pending-reduce annotation is consumed inside a jitted program). So
    ``reshard(Partial -> Replicate)`` is value-preserving: the reduction
    the reference performs across ranks is the identity on the stored
    total, and only the placement metadata changes. Likewise
    ``Partial -> Shard(d)`` re-lays-out the total (the reference's
    reduce-scatter) without changing its value."""
    return shard_tensor(x, mesh, placements)


def shard_optimizer(optimizer, shard_fn=None):
    """Reference: dist.shard_optimizer. Under GSPMD the optimizer states
    inherit the param shardings inside the jitted step automatically, so
    this is a pass-through marker kept for API parity."""
    return optimizer


# ------------------------------------------------------------------ Engine
class Engine:
    """Minimal auto-parallel Engine (reference:
    python/paddle/distributed/auto_parallel/static/engine.py): wraps a
    model + loss + optimizer into a jitted distributed TrainStep and drives
    epochs over a data source."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 strategy=None, mesh: Optional[ProcessMesh] = None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy
        self._process_mesh = mesh
        self._step = None
        self.history: List[float] = []

    def _jax_mesh(self) -> Optional[Mesh]:
        if self._process_mesh is not None:
            return self._process_mesh.to_jax_mesh()
        try:
            from ..fleet.base_topology import get_hybrid_communicate_group
            return get_hybrid_communicate_group().get_mesh()
        except Exception:
            return None

    def prepare(self, data_axes=("dp",)):
        if self._step is None:
            from ...hapi.train_step import TrainStep
            self._step = TrainStep(
                self._model, self._optimizer, loss_fn=self._loss,
                mesh=self._jax_mesh(), data_axes=tuple(data_axes))
        return self._step

    def fit(self, train_data, epochs: int = 1, batch_size: Optional[int] = None,
            steps_per_epoch: Optional[int] = None, verbose: int = 0,
            log_freq: int = 10):
        """train_data: an iterable of (inputs, labels) batches (DataLoader
        or list). Returns the per-step loss history."""
        step = self.prepare()
        for _ in range(epochs):
            for i, batch in enumerate(train_data):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                loss = step(*batch)
                self.history.append(float(loss))
        return self.history

    def evaluate(self, eval_data, steps: Optional[int] = None):
        from ...jit import functional_call
        if self._step is not None:
            # training donated the old param buffers; pull the live ones back
            self._step.sync_to_model()
        self._model.eval()
        params, buffers = self._model.raw_state()
        losses = []
        for i, batch in enumerate(eval_data):
            if steps is not None and i >= steps:
                break
            if self._loss is not None:
                *xs, y = batch
                out = functional_call(self._model, params, *xs,
                                      buffers=buffers)
                from ...jit import tree_to_tensors, tree_to_values
                loss = tree_to_values(self._loss(tree_to_tensors(out), y))
            else:
                loss = functional_call(self._model, params, *batch,
                                       buffers=buffers)
            losses.append(float(np.asarray(loss)))
        return {"loss": float(np.mean(losses)) if losses else float("nan")}

    def state_dict(self):
        if self._step is not None:
            return self._step.state_dict()
        return self._model.state_dict()

    def save(self, path: str):
        from ... import save
        save(self.state_dict(), path)


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """reference: dist.shard_layer — distribute a Layer's parameters over
    ``process_mesh``. ``shard_fn(name, layer, mesh)`` may annotate
    sublayers; the default leaves params replicated (annotations come
    from the parallel layers or dist_attr)."""
    if shard_fn is not None:
        for name, sub in layer.named_sublayers():
            shard_fn(name, sub, process_mesh)
    else:
        jmesh = process_mesh.to_jax_mesh()
        for _, p in layer.named_parameters():
            spec = getattr(p, "dist_attr", None) or P()
            p._value = jax.device_put(p._value, NamedSharding(jmesh, spec))
            p.process_mesh = process_mesh
    return layer


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """reference: dist.to_static — wrap a (sharded) Layer + loss +
    optimizer into an executable distributed program. Returns an Engine
    (prepare() builds the jitted TrainStep)."""
    mesh = getattr(layer, "process_mesh", None)
    for _, p in layer.named_parameters():
        mesh = mesh or getattr(p, "process_mesh", None)
    eng = Engine(layer, loss=loss, optimizer=optimizer, strategy=strategy,
                 mesh=mesh)
    return eng


class Strategy:
    """reference: paddle.distributed.Strategy (auto_parallel strategy
    config: sharding/fused_passes/pipeline knobs). Configuration carrier;
    the Engine reads the fields it understands."""

    def __init__(self, config=None):
        class _NS:
            def __init__(self, **kw):
                self.__dict__.update(kw)
        self.sharding = _NS(enable=False, degree=1, stage=1)
        self.fused_passes = _NS(enable=False, fused_passes_list=[])
        self.pipeline = _NS(enable=False, schedule_mode="1F1B",
                            micro_batch_size=1, accumulate_steps=1)
        self.amp = _NS(enable=False, dtype="float16", level="O1")
        self.gradient_merge = _NS(enable=False, k_steps=1)
        if config:
            for k, v in dict(config).items():
                setattr(self, k, v)


def shard_op(op_fn, process_mesh=None, in_shardings=None,
             out_shardings=None):
    """reference: paddle.distributed.shard_op — annotate one op call with
    input/output shardings. GSPMD formulation: constrain inputs, call,
    constrain outputs."""
    _st = shard_tensor

    def wrapped(*args, **kwargs):
        if in_shardings is not None and process_mesh is not None:
            args = tuple(
                _st(a, process_mesh, s) if s is not None else a
                for a, s in zip(args, in_shardings))
        out = op_fn(*args, **kwargs)
        if out_shardings is not None and process_mesh is not None:
            if isinstance(out, (tuple, list)):
                out = type(out)(
                    _st(o, process_mesh, s) if s is not None else o
                    for o, s in zip(out, out_shardings))
            else:
                out = _st(out, process_mesh, out_shardings[0]
                          if isinstance(out_shardings, (list, tuple))
                          else out_shardings)
        return out
    return wrapped
