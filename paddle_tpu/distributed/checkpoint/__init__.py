"""Distributed (sharded) checkpointing with resharding on load.

Reference: python/paddle/distributed/checkpoint/{save_state_dict,
load_state_dict,metadata}.py — SURVEY.md §5.4. The reference writes
per-rank shard files plus a metadata manifest describing each logical
tensor's global shape and shard layout, then reshards at load time by
intersecting saved shards with the target distribution.

TPU-native design: all of that collapses onto orbax + GSPMD shardings.
A ``jax.Array`` already knows its global shape and per-device layout, so
orbax's TensorStore backend writes exactly the local shards each host owns
(scaling to multi-host without a gather), and restoring with a different
``NamedSharding`` IS the reshard — orbax reads whichever saved chunks the
target layout needs. The manifest the reference hand-rolls is orbax's
checkpoint metadata; we add a small ``paddle_meta.json`` for dtype/shape
assertions and user metadata.

API (reference-shaped):
  - ``save_state_dict(state_dict, path)``
  - ``load_state_dict(state_dict, path)`` — in-place into ``state_dict``'s
    tensors, resharding onto each destination array's sharding
  - ``get_checkpoint_metadata(path)``
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["save_state_dict", "load_state_dict", "get_checkpoint_metadata"]

_META_FILE = "paddle_meta.json"


def _flatten(state_dict: Dict[str, Any], prefix: str = ""):
    """Flatten nested dicts to dot-joined keys -> Tensor/array leaves."""
    flat = {}
    for k, v in state_dict.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten(v, f"{key}."))
        else:
            flat[key] = v
    return flat


def _leaf_value(v):
    from ...core.tensor import Tensor
    return v._value if isinstance(v, Tensor) else jnp.asarray(v)


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id: Optional[int] = None,
                    async_save: bool = False) -> None:
    """Save a (possibly nested) state dict of Tensors / jax.Arrays. Sharded
    arrays write only their local shards per host (orbax/TensorStore);
    replicated arrays write once."""
    import orbax.checkpoint as ocp

    flat = {k: _leaf_value(v) for k, v in _flatten(state_dict).items()}
    if not flat:
        raise ValueError("empty state_dict")
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)

    ckptr = (ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
             if async_save else ocp.PyTreeCheckpointer())
    ckptr.save(os.path.join(path, "state"), flat, force=True)
    if async_save:
        ckptr.wait_until_finished()

    meta = {
        "format_version": 1,
        "unique_id": unique_id,
        "tensors": {
            k: {"shape": list(np.shape(v)), "dtype": str(v.dtype)}
            for k, v in flat.items()
        },
    }
    with open(os.path.join(path, _META_FILE), "w") as f:
        json.dump(meta, f, indent=1)


def get_checkpoint_metadata(path: str) -> Dict[str, Any]:
    with open(os.path.join(os.path.abspath(path), _META_FILE)) as f:
        return json.load(f)


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    offload: bool = False) -> None:
    """Load ``path`` into ``state_dict`` IN PLACE (reference semantics).
    Each destination tensor's current sharding is the target layout: orbax
    restores straight into that ``NamedSharding``, so a checkpoint saved on
    one mesh (e.g. dp4×mp2) loads onto another (dp2×mp4) without a full
    gather anywhere."""
    import orbax.checkpoint as ocp
    from ...core.tensor import Tensor

    path = os.path.abspath(path)
    meta = get_checkpoint_metadata(path)
    flat = _flatten(state_dict)
    missing = [k for k in flat if k not in meta["tensors"]]
    if missing:
        raise KeyError(f"keys not in checkpoint {path}: {missing[:5]}"
                       f"{'...' if len(missing) > 5 else ''}")

    restore_args = {}
    for k, v in flat.items():
        dst = _leaf_value(v)
        saved = meta["tensors"][k]
        if list(dst.shape) != saved["shape"]:
            raise ValueError(
                f"shape mismatch for {k!r}: checkpoint {saved['shape']} vs "
                f"destination {list(dst.shape)}")
        sharding = getattr(dst, "sharding", None)
        restore_args[k] = ocp.ArrayRestoreArgs(
            sharding=sharding, global_shape=tuple(dst.shape),
            dtype=dst.dtype)

    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(
        os.path.join(path, "state"),
        args=ocp.args.PyTreeRestore(restore_args=restore_args))

    for k, v in flat.items():
        val = restored[k]
        if isinstance(v, Tensor):
            v._value = val
        else:
            # raw-array leaf: caller keeps the returned mapping
            flat[k] = val
    # push raw-array updates back into nested structure
    _write_back(state_dict, restored)


def _write_back(state_dict: Dict[str, Any], restored: Dict[str, Any],
                prefix: str = "") -> None:
    from ...core.tensor import Tensor
    for k, v in state_dict.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            _write_back(v, restored, f"{key}.")
        elif not isinstance(v, Tensor) and key in restored:
            state_dict[k] = restored[key]
