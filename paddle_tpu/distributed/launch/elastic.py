"""Elastic manager: bounded-restart supervision over a rendezvous.

Reference: python/paddle/distributed/fleet/elastic/manager.py — an
etcd-backed rendezvous tracks alive nodes; when membership changes or a
worker dies, the manager tears down the gang, re-registers, and relaunches
(up to ``max_restart`` times). SURVEY.md §5.3.

Here the rendezvous is an interface: ``FileRendezvous`` (a shared
directory — works for single-host tests and NFS-backed pods) is provided;
an etcd/GCS-backed one is a drop-in. The supervision loop itself — the
hard part to get right — is fully implemented and tested with killed
subprocesses.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

from .controller import Controller, LaunchContext


class Rendezvous:
    """Membership registry interface (reference: ElasticManager's etcd)."""

    def register(self, node_id: str, info: Dict) -> None:
        raise NotImplementedError

    def deregister(self, node_id: str) -> None:
        raise NotImplementedError

    def alive_nodes(self) -> List[str]:
        raise NotImplementedError

    def barrier(self, world_size: int, timeout: float = 30.0) -> bool:
        """Wait until ``world_size`` nodes are registered."""
        t0 = time.time()
        while time.time() - t0 < timeout:
            if len(self.alive_nodes()) >= world_size:
                return True
            time.sleep(0.1)
        return False


class FileRendezvous(Rendezvous):
    """Directory-backed rendezvous: one JSON file per alive node."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(self.path, exist_ok=True)

    def _file(self, node_id: str) -> str:
        return os.path.join(self.path, f"node.{node_id}.json")

    def register(self, node_id: str, info: Dict) -> None:
        with open(self._file(node_id), "w") as f:
            json.dump({"id": node_id, "ts": time.time(), **info}, f)

    def deregister(self, node_id: str) -> None:
        try:
            os.unlink(self._file(node_id))
        except FileNotFoundError:
            pass

    def alive_nodes(self) -> List[str]:
        out = []
        for name in sorted(os.listdir(self.path)):
            if name.startswith("node.") and name.endswith(".json"):
                out.append(name[len("node."):-len(".json")])
        return out


class ElasticManager:
    """Launch + watch + relaunch loop (reference: ElasticManager.run)."""

    def __init__(self, ctx: LaunchContext,
                 rendezvous: Optional[Rendezvous] = None,
                 node_id: Optional[str] = None,
                 base_env: Optional[Dict[str, str]] = None):
        self.ctx = ctx
        self.rdzv = rendezvous
        self.node_id = node_id or uuid.uuid4().hex[:8]
        self.base_env = base_env
        self.restarts = 0
        self.history: List[int] = []       # gang rc per round

    def run(self, poll_interval: float = 0.2,
            round_timeout: Optional[float] = None) -> int:
        """Supervise until clean exit or restart budget exhausted. Returns
        the final gang rc (0 on success)."""
        while True:
            if self.rdzv is not None:
                self.rdzv.register(self.node_id, {
                    "rank": self.ctx.node_rank,
                    "restarts": self.restarts})
                ok = self.rdzv.barrier(self.ctx.nnodes)
                if not ok:
                    self.rdzv.deregister(self.node_id)
                    return 125          # rendezvous failed to converge
            controller = Controller(self.ctx, base_env=self.base_env)
            controller.start()
            rc = controller.watch(poll_interval=poll_interval,
                                  timeout=round_timeout)
            self.history.append(rc)
            if self.rdzv is not None:
                self.rdzv.deregister(self.node_id)
            if rc == 0:
                return 0
            if self.restarts >= self.ctx.max_restart:
                return rc
            self.restarts += 1
