"""Launch controller: env protocol + process gang supervision.

Reference: python/paddle/distributed/launch/controllers/collective.py —
build per-rank environments, spawn workers, watch, tear down the whole gang
when any member dies (a hung collective cannot make progress with a missing
peer), surface the failing rank's log tail.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass
class LaunchContext:
    """Parsed launch arguments (reference: launch/context/__init__.py)."""
    training_script: str
    training_script_args: List[str] = dataclasses.field(default_factory=list)
    nnodes: int = 1
    node_rank: int = 0
    nproc_per_node: int = 1
    master: Optional[str] = None          # host:port of rank-0 coordinator
    log_dir: str = "log"
    job_id: str = "default"
    devices: Optional[str] = None
    max_restart: int = 0                  # elastic: restarts allowed
    run_module: bool = False              # python -m script

    @property
    def world_size(self) -> int:
        return self.nnodes * self.nproc_per_node

    def rank_env(self, local_rank: int) -> Dict[str, str]:
        """PADDLE_* env protocol for one worker (reference:
        launch/job/pod.py). Endpoints are synthesized host:port pairs; on a
        real multi-host job each host runs one worker and PADDLE_MASTER
        carries the coordinator address."""
        rank = self.node_rank * self.nproc_per_node + local_rank
        master = self.master or "127.0.0.1:8070"
        host = master.split(":")[0]
        base_port = int(master.split(":")[1]) + 1
        endpoints = [f"{host}:{base_port + r}"
                     for r in range(self.world_size)]
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(self.world_size),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_LOCAL_SIZE": str(self.nproc_per_node),
            "PADDLE_MASTER": master,
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_JOB_ID": self.job_id,
            "FLAGS_selected_devices": self.devices or "",
        }
        return env


class Controller:
    """Spawn + watch a local worker gang (reference:
    launch/controllers/controller.py)."""

    def __init__(self, ctx: LaunchContext,
                 base_env: Optional[Dict[str, str]] = None):
        self.ctx = ctx
        self.base_env = dict(os.environ if base_env is None else base_env)
        self.procs: List[subprocess.Popen] = []
        self.log_paths: List[str] = []

    def build_cmd(self) -> List[str]:
        cmd = [sys.executable]
        if self.ctx.run_module:
            cmd.append("-m")
        cmd.append(self.ctx.training_script)
        cmd.extend(self.ctx.training_script_args)
        return cmd

    def start(self) -> None:
        os.makedirs(self.ctx.log_dir, exist_ok=True)
        self.procs, self.log_paths = [], []
        for lr in range(self.ctx.nproc_per_node):
            env = dict(self.base_env)
            env.update(self.ctx.rank_env(lr))
            rank = env["PADDLE_TRAINER_ID"]
            log_path = os.path.join(self.ctx.log_dir,
                                    f"workerlog.{rank}")
            self.log_paths.append(log_path)
            logf = open(log_path, "ab")
            self.procs.append(subprocess.Popen(
                self.build_cmd(), env=env, stdout=logf, stderr=logf,
                start_new_session=True))

    def poll(self) -> Optional[int]:
        """None while all run; first nonzero rc on failure; 0 when all
        exited clean."""
        rcs = [p.poll() for p in self.procs]
        for rc in rcs:
            if rc is not None and rc != 0:
                return rc
        if all(rc == 0 for rc in rcs):
            return 0
        return None

    def watch(self, poll_interval: float = 0.2,
              timeout: Optional[float] = None) -> int:
        """Block until the gang finishes or any member fails (then tear the
        rest down — reference fail-fast semantics). Returns the gang rc."""
        t0 = time.time()
        while True:
            rc = self.poll()
            if rc == 0:
                return 0
            if rc is not None:
                self.stop()
                return rc
            if timeout is not None and time.time() - t0 > timeout:
                self.stop()
                return 124
            time.sleep(poll_interval)

    def stop(self, sig: int = signal.SIGTERM, grace: float = 3.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), sig)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.time() + grace
        for p in self.procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                p.wait()

    def tail_logs(self, n_bytes: int = 2000) -> Dict[str, str]:
        out = {}
        for path in self.log_paths:
            try:
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - n_bytes))
                    out[path] = f.read().decode(errors="replace")
            except OSError:
                out[path] = ""
        return out
