"""Rank-0 HTTP KV master + TCP rendezvous.

Reference: python/paddle/distributed/launch/controllers/master.py
(``HTTPMaster``: rank 0 serves a tiny KV store over HTTP; every node
registers itself and polls the peer list — launch barrier and elastic
membership without etcd or a shared filesystem).

Stdlib-only: ``ThreadingHTTPServer`` on the master, ``urllib`` clients on
the workers — multi-node launch needs nothing but plain TCP to rank 0.

Routes:
  PUT    /kv/<key>        body = value (bytes, stored verbatim)
  GET    /kv/<key>        -> 200 value | 404
  DELETE /kv/<key>
  GET    /prefix/<p>      -> JSON {key: value-as-str} for keys with prefix
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .elastic import Rendezvous


class _Handler(BaseHTTPRequestHandler):
    store: Dict[str, bytes]
    lock: threading.Lock

    def log_message(self, *a):            # silence per-request stderr spam
        pass

    def _key(self) -> Optional[str]:
        if self.path.startswith("/kv/"):
            return self.path[len("/kv/"):]
        return None

    def do_PUT(self):
        key = self._key()
        if key is None:
            self.send_response(404)
            self.end_headers()
            return
        n = int(self.headers.get("Content-Length", 0))
        val = self.rfile.read(n)
        with self.lock:
            self.store[key] = val
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        if self.path.startswith("/prefix/"):
            prefix = self.path[len("/prefix/"):]
            with self.lock:
                out = {k: v.decode("utf-8", "replace")
                       for k, v in self.store.items()
                       if k.startswith(prefix)}
            body = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        key = self._key()
        with self.lock:
            val = self.store.get(key) if key else None
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_DELETE(self):
        key = self._key()
        with self.lock:
            existed = key is not None and self.store.pop(key, None) is not None
        self.send_response(200 if existed else 404)
        self.end_headers()


class KVServer:
    """The rank-0 master: a threaded HTTP KV store."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        handler = type("BoundHandler", (_Handler,), {
            "store": {}, "lock": threading.Lock()})
        self._handler = handler
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)

    def start(self) -> "KVServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class KVClient:
    """urllib client for the master (retries cover master startup races)."""

    def __init__(self, endpoint: str, timeout: float = 5.0,
                 retries: int = 20, retry_interval: float = 0.25):
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.base = endpoint.rstrip("/")
        self.timeout = timeout
        self.retries = retries
        self.retry_interval = retry_interval

    def _req(self, method: str, path: str, data: Optional[bytes] = None,
             want_body: bool = False):
        last = None
        for _ in range(self.retries):
            req = urllib.request.Request(self.base + path, data=data,
                                         method=method)
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    return r.read() if want_body else True
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return None if want_body else False
                last = e
            except (urllib.error.URLError, OSError) as e:
                last = e                   # master not up yet / net blip
            time.sleep(self.retry_interval)
        raise ConnectionError(f"KV master unreachable at {self.base}: {last}")

    def put(self, key: str, value: bytes) -> None:
        self._req("PUT", f"/kv/{key}", data=value)

    def get(self, key: str) -> Optional[bytes]:
        return self._req("GET", f"/kv/{key}", want_body=True)

    def delete(self, key: str) -> None:
        self._req("DELETE", f"/kv/{key}")

    def prefix(self, p: str) -> Dict[str, str]:
        body = self._req("GET", f"/prefix/{p}", want_body=True)
        return json.loads(body) if body else {}


class HTTPRendezvous(Rendezvous):
    """Rendezvous over the rank-0 KV master — the FileRendezvous drop-in
    that works across hosts with no shared filesystem. ``is_master=True``
    (node rank 0) starts the server in-process; every node (including the
    master) talks to it through the client.

    ``ttl``: when set, a registration older than ttl seconds is considered
    dead unless refreshed via ``heartbeat()`` — the reference master's
    etcd-lease behavior for elastic membership."""

    def __init__(self, endpoint: str, is_master: bool = False,
                 ttl: Optional[float] = None):
        self.server: Optional[KVServer] = None
        if is_master:
            host, _, port = endpoint.partition(":")
            self.server = KVServer("0.0.0.0", int(port or 0)).start()
            endpoint = f"{host or '127.0.0.1'}:{self.server.port}"
        self.endpoint = endpoint
        self.client = KVClient(endpoint)
        self.ttl = ttl

    def register(self, node_id: str, info: Dict) -> None:
        self.client.put(f"nodes/{node_id}", json.dumps(
            {"id": node_id, "ts": time.time(), **info}).encode())

    heartbeat = register

    def deregister(self, node_id: str) -> None:
        self.client.delete(f"nodes/{node_id}")

    def alive_nodes(self) -> List[str]:
        now = time.time()
        out = []
        for key, val in sorted(self.client.prefix("nodes/").items()):
            if self.ttl is not None:
                try:
                    if now - json.loads(val)["ts"] > self.ttl:
                        continue
                except (ValueError, KeyError):
                    continue
            out.append(key[len("nodes/"):])
        return out

    def shutdown(self) -> None:
        if self.server is not None:
            self.server.stop()
            self.server = None
