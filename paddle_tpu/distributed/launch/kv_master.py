"""Rank-0 HTTP KV master + TCP rendezvous.

Reference: python/paddle/distributed/launch/controllers/master.py
(``HTTPMaster``: rank 0 serves a tiny KV store over HTTP; every node
registers itself and polls the peer list — launch barrier and elastic
membership without etcd or a shared filesystem).

Stdlib-only: ``ThreadingHTTPServer`` on the master, ``urllib`` clients on
the workers — multi-node launch needs nothing but plain TCP to rank 0.

Hardening (advisor r3): the server binds the master endpoint's interface
(not 0.0.0.0) when one is given, and when a job token is set (explicitly
or via ``PADDLE_JOB_TOKEN``) every request must carry it in an
``X-Job-Token`` header — any host that can reach the port can no longer
read or rewrite the rendezvous state.

Routes:
  PUT    /kv/<key>        body = value (bytes, stored verbatim)
  GET    /kv/<key>        -> 200 value | 404
  DELETE /kv/<key>
  GET    /prefix/<p>      -> JSON {key: value-as-str} for keys with prefix
"""

from __future__ import annotations

import hmac
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .elastic import Rendezvous


def check_job_token(handler: BaseHTTPRequestHandler,
                    token: Optional[str]) -> bool:
    """Shared X-Job-Token gate (used by the KV master AND distributed.rpc
    so a hardening change lands in both): constant-time compare, 403 +
    False on mismatch. Call BEFORE reading or unpickling the body."""
    if token and not hmac.compare_digest(
            handler.headers.get("X-Job-Token", ""), token):
        try:   # drain the body so the client sees 403, not a RST reset;
            # attacker-controlled headers: a junk Content-Length must not
            # crash the rejection path, and an inflated one must not pin
            # this thread on a blocking read
            handler.connection.settimeout(5.0)
            n = int(handler.headers.get("Content-Length", 0) or 0)
            while n > 0:
                chunk = handler.rfile.read(min(n, 1 << 16))
                if not chunk:
                    break
                n -= len(chunk)
        except (OSError, ValueError):
            pass
        handler.send_response(403)
        handler.end_headers()
        return False
    return True


class _Handler(BaseHTTPRequestHandler):
    store: Dict[str, bytes]
    lock: threading.Lock
    token: Optional[str]

    def log_message(self, *a):            # silence per-request stderr spam
        pass

    def _authorized(self) -> bool:
        return check_job_token(self, self.token)

    def _key(self) -> Optional[str]:
        if self.path.startswith("/kv/"):
            return self.path[len("/kv/"):]
        return None

    def do_PUT(self):
        if not self._authorized():
            return
        key = self._key()
        if key is None:
            self.send_response(404)
            self.end_headers()
            return
        n = int(self.headers.get("Content-Length", 0))
        val = self.rfile.read(n)
        with self.lock:
            self.store[key] = val
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        if not self._authorized():
            return
        if self.path.startswith("/prefix/"):
            prefix = self.path[len("/prefix/"):]
            with self.lock:
                out = {k: v.decode("utf-8", "replace")
                       for k, v in self.store.items()
                       if k.startswith(prefix)}
            body = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        key = self._key()
        with self.lock:
            val = self.store.get(key) if key else None
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_DELETE(self):
        if not self._authorized():
            return
        key = self._key()
        with self.lock:
            existed = key is not None and self.store.pop(key, None) is not None
        self.send_response(200 if existed else 404)
        self.end_headers()


class KVServer:
    """The rank-0 master: a threaded HTTP KV store."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 token: Optional[str] = None):
        handler = type("BoundHandler", (_Handler,), {
            "store": {}, "lock": threading.Lock(), "token": token})
        self._handler = handler
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)

    def start(self) -> "KVServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class KVClient:
    """urllib client for the master (retries cover master startup races)."""

    def __init__(self, endpoint: str, timeout: float = 5.0,
                 retries: int = 20, retry_interval: float = 0.25,
                 token: Optional[str] = None):
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.base = endpoint.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.retries = retries
        self.retry_interval = retry_interval

    def _req(self, method: str, path: str, data: Optional[bytes] = None,
             want_body: bool = False):
        last = None
        for _ in range(self.retries):
            req = urllib.request.Request(self.base + path, data=data,
                                         method=method)
            if self.token:
                req.add_header("X-Job-Token", self.token)
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    return r.read() if want_body else True
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return None if want_body else False
                if e.code == 403:   # deterministic: wrong/missing job token
                    raise PermissionError(
                        f"KV master at {self.base} rejected the job token "
                        "(set PADDLE_JOB_TOKEN to match the master)") from e
                last = e
            except (urllib.error.URLError, OSError) as e:
                last = e                   # master not up yet / net blip
            time.sleep(self.retry_interval)
        raise ConnectionError(f"KV master unreachable at {self.base}: {last}")

    def put(self, key: str, value: bytes) -> None:
        self._req("PUT", f"/kv/{key}", data=value)

    def get(self, key: str) -> Optional[bytes]:
        return self._req("GET", f"/kv/{key}", want_body=True)

    def delete(self, key: str) -> None:
        self._req("DELETE", f"/kv/{key}")

    def prefix(self, p: str) -> Dict[str, str]:
        body = self._req("GET", f"/prefix/{p}", want_body=True)
        return json.loads(body) if body else {}


class HTTPRendezvous(Rendezvous):
    """Rendezvous over the rank-0 KV master — the FileRendezvous drop-in
    that works across hosts with no shared filesystem. ``is_master=True``
    (node rank 0) starts the server in-process; every node (including the
    master) talks to it through the client.

    ``ttl``: when set, a registration older than ttl seconds is considered
    dead unless refreshed via ``heartbeat()`` — the reference master's
    etcd-lease behavior for elastic membership."""

    def __init__(self, endpoint: str, is_master: bool = False,
                 ttl: Optional[float] = None,
                 token: Optional[str] = None):
        import os
        if token is None:
            token = os.environ.get("PADDLE_JOB_TOKEN") or None
        self.server: Optional[KVServer] = None
        if is_master:
            host, _, port = endpoint.partition(":")
            # bind the advertised interface when it is a literal IP;
            # hostnames may resolve to loopback locally (Debian-style
            # /etc/hosts) where binding would succeed yet be unreachable
            # from peers, so they get 0.0.0.0 + token auth instead
            bind_host = "0.0.0.0"
            if host:
                try:
                    import ipaddress
                    ipaddress.ip_address(host)
                    bind_host = host
                except ValueError:
                    pass
            try:
                self.server = KVServer(bind_host, int(port or 0),
                                       token=token).start()
            except OSError:
                self.server = KVServer("0.0.0.0", int(port or 0),
                                       token=token).start()
            endpoint = f"{host or '127.0.0.1'}:{self.server.port}"
        self.endpoint = endpoint
        self.client = KVClient(endpoint, token=token)
        self.ttl = ttl

    def register(self, node_id: str, info: Dict) -> None:
        self.client.put(f"nodes/{node_id}", json.dumps(
            {"id": node_id, "ts": time.time(), **info}).encode())

    heartbeat = register

    def deregister(self, node_id: str) -> None:
        self.client.delete(f"nodes/{node_id}")

    def alive_nodes(self) -> List[str]:
        now = time.time()
        out = []
        for key, val in sorted(self.client.prefix("nodes/").items()):
            if self.ttl is not None:
                try:
                    if now - json.loads(val)["ts"] > self.ttl:
                        continue
                except (ValueError, KeyError):
                    continue
            out.append(key[len("nodes/"):])
        return out

    def shutdown(self) -> None:
        if self.server is not None:
            self.server.stop()
            self.server = None
