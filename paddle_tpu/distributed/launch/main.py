"""``python -m paddle_tpu.distributed.launch`` entry point.

Reference: python/paddle/distributed/launch/main.py (argument surface) —
the subset meaningful on TPU jobs is kept; PS-mode / ips-file arguments are
rejected with guidance.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .controller import LaunchContext
from .elastic import ElasticManager, FileRendezvous


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a distributed paddle_tpu job "
                    "(one process per host; PADDLE_* env protocol)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of hosts in the job")
    p.add_argument("--node_rank", "--rank", type=int, default=0,
                   dest="node_rank", help="this host's rank")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="workers per host (1 for real TPU jobs; >1 for "
                        "CPU-simulated testing)")
    p.add_argument("--master", type=str, default=None,
                   help="rank-0 coordinator host:port")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--devices", "--gpus", "--xpus", type=str, default=None,
                   dest="devices", help="visible accelerator ids")
    p.add_argument("--max_restart", type=int, default=0,
                   help="elastic restart budget (0 = fail fast)")
    p.add_argument("--elastic_rdzv_dir", type=str, default=None,
                   help="shared dir for the file rendezvous (elastic mode)")
    p.add_argument("-m", "--module", action="store_true",
                   help="run training_script as a module (python -m)")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p


def launch(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    ctx = LaunchContext(
        training_script=args.training_script,
        training_script_args=list(args.training_script_args),
        nnodes=args.nnodes, node_rank=args.node_rank,
        nproc_per_node=args.nproc_per_node, master=args.master,
        log_dir=args.log_dir, job_id=args.job_id, devices=args.devices,
        max_restart=args.max_restart, run_module=args.module)
    if args.elastic_rdzv_dir:
        rdzv = FileRendezvous(args.elastic_rdzv_dir)
    elif args.master and args.nnodes > 1:
        # multi-node without a shared FS: rank 0 serves the HTTP KV master
        # (reference: launch/controllers/master.py), everyone rendezvous
        # against it over plain TCP
        from .kv_master import HTTPRendezvous
        rdzv = HTTPRendezvous(args.master, is_master=args.node_rank == 0)
    else:
        rdzv = None
    mgr = ElasticManager(ctx, rendezvous=rdzv)
    rc = mgr.run()
    if rc != 0:
        sys.stderr.write(
            f"[launch] job failed rc={rc} after {mgr.restarts} restarts; "
            f"log tails:\n")
        from .controller import Controller
        c = Controller(ctx)
        c.log_paths = [
            f"{ctx.log_dir}/workerlog.{ctx.node_rank * ctx.nproc_per_node + i}"
            for i in range(ctx.nproc_per_node)]
        for path, tail in c.tail_logs().items():
            sys.stderr.write(f"----- {path} -----\n{tail}\n")
    return rc


def main() -> None:
    sys.exit(launch())
