"""Distributed launch CLI + restart supervisor.

Reference: python/paddle/distributed/launch/ (``python -m
paddle.distributed.launch``): argument context, PADDLE_* env protocol,
per-rank log files, a controller that spawns/watches/tears-down workers,
and the elastic manager (fleet/elastic/manager.py) that relaunches on
failure — SURVEY.md §1 L6 + §5.3.

TPU-native mapping: a JAX job runs ONE process per host (all local chips
belong to it), so ``--nproc_per_node`` defaults to 1 and rank == node id.
The launcher's real job is the env protocol (PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / PADDLE_MASTER / PADDLE_TRAINER_ENDPOINTS — consumed
by ``init_parallel_env`` -> ``jax.distributed.initialize``) plus process
supervision: per-rank logs, fail-fast teardown of the whole gang, and
bounded elastic restarts with a fresh rendezvous each round. Multi-process-
per-host is still supported for CPU-simulated testing.
"""

from .main import launch, main  # noqa: F401
from .controller import Controller, LaunchContext  # noqa: F401
from .elastic import ElasticManager, FileRendezvous, Rendezvous  # noqa: F401
