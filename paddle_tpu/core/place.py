"""Device placement facade.

Reference: phi::Place / DeviceContext (paddle/phi/common/place.h,
paddle/phi/core/device_context.h). On TPU, PJRT owns streams and memory, so
a Place is a thin handle to a ``jax.Device`` and the DeviceContext reduces
to device selection + default-dtype state. ``set_device('tpu')`` /
``get_device()`` mirror ``paddle.set_device`` / ``paddle.get_device``.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from .enforce import InvalidArgumentError


class Place:
    """Base place: a handle to a jax device."""

    device_type = "undefined"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __repr__(self) -> str:
        return f"Place({self.device_type}:{self._device_id})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self._device_id == other._device_id
        )

    def __hash__(self) -> int:
        return hash((self.device_type, self._device_id))

    def jax_device(self) -> Optional[jax.Device]:
        devs = [d for d in jax.devices() if _platform_of(d) == self.device_type]
        if not devs:
            devs = jax.devices()  # fall back to default platform
        return devs[min(self._device_id, len(devs) - 1)]

    def is_cpu_place(self) -> bool:
        return self.device_type == "cpu"

    def is_tpu_place(self) -> bool:
        return self.device_type == "tpu"

    # GPU never exists in this stack; kept for source compatibility.
    def is_gpu_place(self) -> bool:
        return False


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    device_type = "tpu"


# Source-compat aliases: code written against the reference's CUDA places
# runs unchanged on the TPU build.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace
CustomPlace = TPUPlace


def _platform_of(d: jax.Device) -> str:
    p = d.platform.lower()
    # the axon PJRT plugin reports platform 'axon' for a TPU chip
    return "tpu" if p in ("tpu", "axon") else p


class _DeviceState(threading.local):
    def __init__(self):
        self.place: Optional[Place] = None
        self.default_dtype = "float32"


_state = _DeviceState()


def _default_place() -> Place:
    plats = {_platform_of(d) for d in jax.devices()}
    return TPUPlace(0) if "tpu" in plats else CPUPlace(0)


def set_device(device: str) -> Place:
    """``paddle.set_device`` analogue. Accepts 'cpu', 'tpu', 'tpu:N';
    'gpu'/'xpu' map to tpu for source compatibility."""
    dev = device.lower()
    if ":" in dev:
        kind, _, idx = dev.partition(":")
        idx = int(idx)
    else:
        kind, idx = dev, 0
    if kind in ("tpu", "gpu", "cuda", "xpu", "npu", "custom_device"):
        place: Place = TPUPlace(idx)
    elif kind == "cpu":
        place = CPUPlace(idx)
    else:
        raise InvalidArgumentError(f"Unknown device {device!r}")
    _state.place = place
    return place


def get_device() -> str:
    p = current_place()
    return f"{p.device_type}:{p.get_device_id()}"


def current_place() -> Place:
    if _state.place is None:
        _state.place = _default_place()
    return _state.place


def set_default_dtype(dtype) -> None:
    from .dtype import to_paddle_dtype

    _state.default_dtype = to_paddle_dtype(dtype).name


def get_default_dtype() -> str:
    return _state.default_dtype


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def is_compiled_with_xpu() -> bool:
    return False


def device_count() -> int:
    return len(jax.devices())
