from . import autograd, dtype, enforce, place, tensor  # noqa: F401
from .tensor import Parameter, Tensor, apply_op  # noqa: F401
