"""Dtype system.

Paddle-shaped dtype objects (``paddle.float32`` etc. — reference:
paddle/phi/common/data_type.h) backed by numpy/jax dtypes. A ``DType``
compares equal to its string name, to the numpy dtype, and to other DType
instances, so user code written against either convention works.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


class DType:
    __slots__ = ("name", "np_dtype")
    _registry: dict = {}

    def __new__(cls, name: str, np_dtype):
        if name in cls._registry:
            return cls._registry[name]
        self = super().__new__(cls)
        object.__setattr__(self, "name", name)
        object.__setattr__(self, "np_dtype", np.dtype(np_dtype))
        cls._registry[name] = self
        return self

    def __setattr__(self, *a):  # immutable
        raise AttributeError("DType is immutable")

    def __repr__(self) -> str:
        return f"paddle_tpu.{self.name}"

    def __str__(self) -> str:
        return self.name

    def __hash__(self) -> int:
        return hash(self.name)

    def __eq__(self, other) -> bool:
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            return self.name == other or str(self.np_dtype) == other
        try:
            return np.dtype(other) == self.np_dtype
        except TypeError:
            return NotImplemented

    @property
    def is_floating_point(self) -> bool:
        return jnp.issubdtype(self.np_dtype, np.floating)

    @property
    def is_integer(self) -> bool:
        return jnp.issubdtype(self.np_dtype, np.integer)

    @property
    def is_complex(self) -> bool:
        return jnp.issubdtype(self.np_dtype, np.complexfloating)

    @property
    def itemsize(self) -> int:
        return self.np_dtype.itemsize


bfloat16 = DType("bfloat16", jnp.bfloat16)
float16 = DType("float16", np.float16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
int8 = DType("int8", np.int8)
uint8 = DType("uint8", np.uint8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
bool_ = DType("bool", np.bool_)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)
float8_e4m3fn = DType("float8_e4m3fn", jnp.float8_e4m3fn)
float8_e5m2 = DType("float8_e5m2", jnp.float8_e5m2)

_BY_NAME = dict(DType._registry)
_BY_NAME["bool"] = bool_
_BY_NAME["float"] = float32
_BY_NAME["double"] = float64
_BY_NAME["half"] = float16
_BY_NAME["int"] = int32
_BY_NAME["long"] = int64


def to_paddle_dtype(dtype) -> DType:
    """Coerce str / numpy dtype / jax dtype / DType to a DType."""
    if isinstance(dtype, DType):
        return dtype
    if isinstance(dtype, str):
        if dtype in _BY_NAME:
            return _BY_NAME[dtype]
        return DType(str(np.dtype(dtype)), np.dtype(dtype))
    npd = np.dtype(dtype)
    name = "bfloat16" if npd == jnp.bfloat16 else str(npd)
    if name in _BY_NAME:
        return _BY_NAME[name]
    return DType(name, npd)


def to_jax_dtype(dtype):
    """Coerce any dtype spec to the numpy/jax dtype object jnp accepts."""
    if dtype is None:
        return None
    return to_paddle_dtype(dtype).np_dtype


def is_floating(dtype) -> bool:
    return to_paddle_dtype(dtype).is_floating_point


class finfo:
    """reference: paddle.finfo — float dtype limits."""

    def __init__(self, dtype):
        d = (dtype.np_dtype if isinstance(dtype, DType)
             else to_jax_dtype(dtype))
        import ml_dtypes
        info = ml_dtypes.finfo(d)
        self.dtype = str(np.dtype(d))
        self.bits = info.bits
        self.eps = float(info.eps)
        self.min = float(info.min)
        self.max = float(info.max)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.tiny)
        self.resolution = float(info.resolution)


class iinfo:
    """reference: paddle.iinfo — integer dtype limits."""

    def __init__(self, dtype):
        d = (dtype.np_dtype if isinstance(dtype, DType)
             else to_jax_dtype(dtype))
        info = np.iinfo(np.dtype(d))
        self.dtype = str(np.dtype(d))
        self.bits = info.bits
        self.min = int(info.min)
        self.max = int(info.max)
