"""Tensor: the user-facing array type.

Reference: phi::DenseTensor + the pybind eager Tensor
(paddle/phi/core/dense_tensor.h, paddle/fluid/pybind/eager_method.cc).
Here a Tensor is a thin mutable handle around an immutable ``jax.Array``
(or a jax tracer inside jit), carrying paddle-style metadata: ``name``,
``stop_gradient``, ``persistable``, ``grad``. All math dispatches through
``apply_op`` so the eager tape (core/autograd.py) can record.

Most operator methods are monkey-bound by ``paddle_tpu.ops`` at import time,
mirroring how the reference patches generated methods onto the pybind Tensor.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from . import autograd
from .dtype import DType, to_jax_dtype, to_paddle_dtype
from .place import CPUPlace, Place, TPUPlace, current_place, get_default_dtype

_name_counter = threading.local()


def _auto_name(prefix="generated_tensor"):
    n = getattr(_name_counter, "n", 0)
    _name_counter.n = n + 1
    return f"{prefix}_{n}"


# jit/sot capture hooks: a creation sequence number distinguishes tensors
# born during a capture from pre-existing free variables, and the force
# listener observes every tensor-data -> Python crossing (guard points)
_seq = 0
_force_listener = None   # set by jit/sot during a capture run
_sot_recorder = None     # set by jit/sot during a capture run


def _next_seq() -> int:
    global _seq
    _seq += 1
    return _seq


def _notify_force(t, kind: str, value):
    if _force_listener is not None:
        _force_listener(t, kind, value)
    return value


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "persistable",
        "name",
        "grad",
        "_grad_node",
        "_out_index",
        "_retain_grads",
        "_backward_hooks",
        "_seq",             # creation sequence number (jit/sot capture)
        "_static_var_id",   # static Program variable id (static/program.py)
        "dist_attr",        # sharding annotation (auto_parallel): PartitionSpec
        "process_mesh",     # auto_parallel ProcessMesh (shard_tensor output)
        "placements",       # auto_parallel placements list (shard_tensor)
        "__weakref__",
    )

    def __init__(
        self,
        value,
        dtype=None,
        place: Optional[Place] = None,
        stop_gradient: bool = True,
        name: Optional[str] = None,
        persistable: bool = False,
    ):
        if isinstance(value, Tensor):
            value = value._value
        if isinstance(value, jax.ShapeDtypeStruct):
            # meta tensor (LazyGuard): shape+dtype metadata, no storage
            pass
        elif not isinstance(value, jax.Array) and not isinstance(value, jax.core.Tracer):
            value = jnp.asarray(value, dtype=to_jax_dtype(dtype))
        elif dtype is not None and jnp.result_type(value) != to_jax_dtype(dtype):
            value = value.astype(to_jax_dtype(dtype))
        if place is not None and isinstance(value, jax.Array):
            dev = place.jax_device()
            if dev is not None:
                value = jax.device_put(value, dev)
        self._value = value
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.name = name or _auto_name()
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self._retain_grads = False
        self._backward_hooks = []
        self._seq = _next_seq()

    # ------------------------------------------------------------------ meta
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self) -> int:
        return self._value.ndim

    @property
    def size(self) -> int:
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self) -> DType:
        return to_paddle_dtype(jnp.result_type(self._value))

    @property
    def place(self) -> Place:
        try:
            dev = self._value.devices()
            plat = next(iter(dev)).platform.lower()
            if plat in ("tpu", "axon"):
                return TPUPlace(next(iter(dev)).id)
            return CPUPlace(0)
        except Exception:
            return current_place()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    def numel(self) -> int:
        return self.size

    def element_size(self) -> int:
        return self.dtype.itemsize

    # -------------------------------------------------------------- convert
    def numpy(self) -> np.ndarray:
        return _notify_force(self, "array", np.asarray(self._value))

    def item(self):
        v = self._value.item() if hasattr(self._value, "item") else np.asarray(self._value).item()
        return _notify_force(self, "item", v)

    def tolist(self):
        return _notify_force(self, "array", np.asarray(self._value).tolist())

    def __array__(self, dtype=None):
        arr = np.asarray(self._value)
        return _notify_force(
            self, "array", arr.astype(dtype) if dtype is not None else arr)

    def astype(self, dtype) -> "Tensor":
        return apply_op("cast", lambda x: x.astype(to_jax_dtype(dtype)), self)

    cast = astype

    def cpu(self) -> "Tensor":
        return Tensor(jax.device_put(self._value, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient, name=self.name)

    def to(self, *args, **kwargs) -> "Tensor":
        dtype = kwargs.get("dtype")
        device = kwargs.get("device")
        for a in args:
            if isinstance(a, str) and a.split(":")[0] in ("cpu", "tpu", "gpu", "cuda"):
                device = a
            elif isinstance(a, (str, DType)):
                dtype = a
            elif isinstance(a, Place):
                device = f"{a.device_type}:{a.get_device_id()}"
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            from .place import set_device  # noqa: F401  (validates names)
            kind = device.split(":")[0]
            plat = "cpu" if kind == "cpu" else None
            devs = jax.devices(plat) if plat else jax.devices()
            idx = int(device.split(":")[1]) if ":" in device else 0
            out = Tensor(
                jax.device_put(out._value, devs[min(idx, len(devs) - 1)]),
                stop_gradient=out.stop_gradient,
                name=out.name,
            )
        return out

    # ------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph: bool = False) -> None:
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def retain_grads(self) -> None:
        self._retain_grads = True

    def clear_grad(self) -> None:
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name + ".detach")
        if _sot_recorder is not None:
            _sot_recorder.on_alias(self, t, stopped=True)
        return t

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        if _sot_recorder is not None:
            _sot_recorder.on_alias(self, self, stopped=True)
        return self

    def _accumulate_grad(self, gval) -> None:
        if gval.dtype != jnp.result_type(self._value):
            gval = gval.astype(jnp.result_type(self._value))
        for hook in self._backward_hooks:
            out = hook(Tensor(gval, stop_gradient=True))
            if out is not None:
                gval = out._value if isinstance(out, Tensor) else out
        if self.grad is None:
            self.grad = Tensor(gval, stop_gradient=True, name=self.name + "@GRAD")
        else:
            self.grad = Tensor(self.grad._value + gval, stop_gradient=True,
                               name=self.name + "@GRAD")

    def register_hook(self, hook: Callable) -> Callable:
        """Hook called with the gradient when it is accumulated into this
        tensor (paddle's Tensor.register_hook)."""
        self._backward_hooks.append(hook)

        def remove():
            if hook in self._backward_hooks:
                self._backward_hooks.remove(hook)

        remove.remove = remove
        return remove

    # ---------------------------------------------------------- in-place ops
    # every in-place path funnels through set_value/_inplace/__setitem__;
    # an active jit/sot capture cannot represent mutation, so notify it
    def set_value(self, value) -> None:
        if _sot_recorder is not None:
            _sot_recorder.on_mutation(self)
        if isinstance(value, Tensor):
            value = value._value
        self._value = jnp.asarray(value, dtype=jnp.result_type(self._value))

    def copy_(self, other: "Tensor") -> "Tensor":
        self.set_value(other)
        return self

    def _inplace(self, new_value) -> "Tensor":
        if _sot_recorder is not None:
            _sot_recorder.on_mutation(self)
        self._value = new_value
        return self

    def add_(self, y) -> "Tensor":
        return self._inplace(self._value + _val(y))

    def subtract_(self, y) -> "Tensor":
        return self._inplace(self._value - _val(y))

    def multiply_(self, y) -> "Tensor":
        return self._inplace(self._value * _val(y))

    def scale_(self, scale: float, bias: float = 0.0) -> "Tensor":
        return self._inplace(self._value * scale + bias)

    def zero_(self) -> "Tensor":
        return self._inplace(jnp.zeros_like(self._value))

    def fill_(self, v) -> "Tensor":
        return self._inplace(jnp.full_like(self._value, v))

    def clip_(self, min=None, max=None) -> "Tensor":
        return self._inplace(jnp.clip(self._value, min, max))

    # ------------------------------------------------------------- indexing
    def __getitem__(self, idx) -> "Tensor":
        idx = _val_index(idx)
        return apply_op("getitem", lambda x: x[idx], self)

    def __setitem__(self, idx, v) -> None:
        if _sot_recorder is not None:
            _sot_recorder.on_mutation(self)
        idx = _val_index(idx)
        self._value = self._value.at[idx].set(_val(v))

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -------------------------------------------------------------- display
    def __repr__(self) -> str:
        sg = self.stop_gradient
        if isinstance(self._value, jax.core.Tracer):
            return f"Tensor(shape={self.shape}, dtype={self.dtype.name}, traced)"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place}, stop_gradient={sg},\n{np.asarray(self._value)})"
        )

    def __bool__(self) -> bool:
        return _notify_force(self, "bool", bool(np.asarray(self._value)))

    def __int__(self) -> int:
        return _notify_force(self, "int", int(np.asarray(self._value)))

    def __float__(self) -> float:
        return _notify_force(self, "float", float(np.asarray(self._value)))

    def __index__(self) -> int:
        # lets a scalar int Tensor drive range()/slicing; under tracing
        # jax raises its concretization error, which to_static's guard
        # turns into guidance (instead of range()'s bare TypeError)
        return _notify_force(self, "int", self._value.__index__())

    def __hash__(self):
        return id(self)

    # Arithmetic dunders are bound in paddle_tpu/ops/__init__.py.


# ----------------------------------------------------- sot mutation watch
# During a jit/sot capture, EVERY reassignment of an existing tensor's
# ``_value`` (in-place ops spread across the op modules, optimizer steps,
# BatchNorm stat updates, functional_call swaps of nested jits) is a
# mutation the pure replay tape cannot represent. Rather than patching
# every site, the capture temporarily replaces the ``_value`` slot
# descriptor with a watching property — zero overhead outside capture,
# complete coverage during it. Initial assignment (slot still unset, i.e.
# tensor construction) stays silent.
_VALUE_MEMBER = Tensor.__dict__["_value"]


def _watched_get(self):
    return _VALUE_MEMBER.__get__(self, Tensor)


def _watched_set(self, v):
    try:
        _VALUE_MEMBER.__get__(self, Tensor)
        existed = True
    except AttributeError:
        existed = False
    if existed and _sot_recorder is not None:
        _sot_recorder.on_mutation(self)
    _VALUE_MEMBER.__set__(self, v)


_WATCH_PROPERTY = property(_watched_get, _watched_set)


def _install_mutation_watch() -> None:
    Tensor._value = _WATCH_PROPERTY


def _remove_mutation_watch() -> None:
    Tensor._value = _VALUE_MEMBER


class Parameter(Tensor):
    """Trainable tensor (paddle's EagerParamBase): stop_gradient=False,
    persistable, optionally ``trainable`` togglable."""

    __slots__ = ("optimize_attr", "is_distributed", "split_axis",
                 "sequence_parallel", "_lazy_init")

    def __init__(self, value, dtype=None, name=None, trainable: bool = True):
        super().__init__(
            value,
            dtype=dtype,
            stop_gradient=not trainable,
            name=name or _auto_name("param"),
            persistable=True,
        )
        self.optimize_attr = {"learning_rate": 1.0}
        self.is_distributed = False
        self.split_axis = None  # set by TP layers: axis this param is sharded on

    @property
    def trainable(self) -> bool:
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v: bool) -> None:
        self.stop_gradient = not v


def _val(x):
    return x._value if isinstance(x, Tensor) else x


def _val_index(idx):
    if isinstance(idx, tuple):
        return tuple(_val(i) for i in idx)
    return _val(idx)


# set by static/program.py while a program_guard is active: every op
# through this dispatch point is then also recorded into the Program
_static_recorder = None


def apply_op(name: str, fn: Callable, *args, **kwargs) -> Any:
    """Single dispatch point for every eager op.

    ``args`` may mix Tensors and raw values; ``kwargs`` are static (shapes,
    axes). Executes via jax, records a GradNode when grads are required
    (see core/autograd.py), and wraps outputs as Tensors. Under an active
    ``paddle.static.program_guard`` the op is additionally recorded for
    Executor replay.
    """
    from .. import flags

    tensor_args = [a if isinstance(a, Tensor) else None for a in args]
    values = tuple(a._value if isinstance(a, Tensor) else a for a in args)
    values = _maybe_amp_cast(name, values)
    out, node = autograd.record_op(name, fn, tensor_args, values, kwargs)

    # deliberate per-op registry read: check_nan_inf is a runtime-
    # toggleable debug switch (set_flags mid-run must take effect on the
    # next eager op) and the check itself skips tracers, so no value is
    # ever baked into a compiled program  # tracecheck: disable=TRC001
    if flags.get_flag("check_nan_inf"):
        _check_nan_inf(name, out)

    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    wrapped = []
    for i, o in enumerate(outs):
        # tuples pass through un-wrapped even when they expose a .dtype
        # (QuantizedPages rides ops as an array-of-arrays NamedTuple)
        if o is None or isinstance(o, tuple) or not hasattr(o, "dtype"):
            wrapped.append(o)
            continue
        t = Tensor(o, stop_gradient=(node is None), name=f"{name}_out")
        if node is not None:
            t._grad_node = node
            t._out_index = i
        wrapped.append(t)
    if _static_recorder is not None:
        _static_recorder.record(name, fn, args, kwargs, wrapped)
    if _sot_recorder is not None:
        _sot_recorder.record(name, fn, args, kwargs, wrapped, multi)
    return tuple(wrapped) if multi else wrapped[0]


def _maybe_amp_cast(name: str, values):
    """AMP casting at the dispatch point — the reference does this in C++
    eager dispatch (paddle/fluid/eager/amp_utils.h)."""
    from ..amp.auto_cast import amp_state, black_list, white_list

    st = amp_state()
    if st is None:
        return values
    from .dtype import to_jax_dtype

    target = to_jax_dtype(st.dtype)
    if st.level == "O2":
        do_cast = name not in black_list()
    else:
        do_cast = name in white_list()
    if not do_cast:
        # black-listed ops promote low-precision inputs to fp32
        if name in black_list():
            return tuple(
                v.astype(jnp.float32)
                if hasattr(v, "dtype") and jnp.result_type(v) in (jnp.bfloat16, jnp.float16)
                else v
                for v in values)
        return values
    return tuple(
        v.astype(target)
        if hasattr(v, "dtype") and jnp.result_type(v) == jnp.float32
        else v
        for v in values)


def _check_nan_inf(op_name: str, out) -> None:
    """FLAGS_check_nan_inf analogue (reference: nan_inf_utils_detail)."""
    import numpy as _np

    from ..amp.debugging import record_op_stats
    record_op_stats(op_name, out)  # no-op unless a dump dir is configured

    outs = out if isinstance(out, (tuple, list)) else (out,)
    for o in outs:
        if o is None or not hasattr(o, "dtype"):
            continue
        if not jnp.issubdtype(jnp.result_type(o), jnp.floating):
            continue
        if isinstance(o, jax.core.Tracer):
            continue
        arr = _np.asarray(o)
        if not _np.isfinite(arr).all():
            from .. import flags as _flags
            msg = f"Operator {op_name!r} output contains NaN or Inf."
            # error-path only, tracers already filtered above
            # tracecheck: disable=TRC001
            if _flags.get_flag("check_nan_inf_level") == 0:
                raise FloatingPointError(msg)
            print("WARNING:", msg)
