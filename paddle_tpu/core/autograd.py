"""Eager autograd engine.

TPU-native redesign of the reference's dygraph engine
(paddle/fluid/eager/backward.cc, grad_node_info.h): the reference code-generates
a C++ GradNode class per op; here every op records ONE generic node whose
backward is the ``jax.vjp`` of the op's jax implementation. ``backward()`` runs
a reverse-topological sweep over the recorded DAG, exactly like
``egr::Backward``'s ready-queue, accumulating into leaf ``Tensor.grad``.

Eager mode is the debuggable path; the performance path wraps whole train
steps in ``jax.jit`` via ``paddle_tpu.jit`` where this tape is bypassed and
``jax.grad`` differentiates the traced program.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True          # paddle.no_grad toggles this
        self.functional = 0          # >0 inside jit tracing: bypass the tape


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled and _state.functional == 0


@contextlib.contextmanager
def no_grad_guard():
    prev = _state.enabled
    _state.enabled = False
    try:
        yield
    finally:
        _state.enabled = prev


class no_grad(contextlib.ContextDecorator):
    """``paddle.no_grad``: context manager and decorator."""

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = False
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = True
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False


def set_grad_enabled(mode: bool):
    class _Ctx:
        def __init__(self):
            self._prev = _state.enabled
            _state.enabled = bool(mode)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            _state.enabled = self._prev
            return False

    return _Ctx()


@contextlib.contextmanager
def functional_guard():
    """Inside jit tracing: ops execute but the tape does not record."""
    _state.functional += 1
    try:
        yield
    finally:
        _state.functional -= 1


def in_functional_mode() -> bool:
    return _state.functional > 0


class GradNode:
    """One recorded op. ``vjp_fn`` maps output cotangents -> input cotangents
    for the float inputs that required grad (``inputs``)."""

    __slots__ = (
        "name",
        "vjp_fn",
        "inputs",
        "out_avals",
        "__weakref__",
    )

    def __init__(self, name, vjp_fn, inputs, out_avals):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # list[Tensor] (floats that require grad)
        self.out_avals = out_avals    # list[(shape, dtype)] of op outputs

    def parents(self):
        return [t._grad_node for t in self.inputs if t._grad_node is not None]

    def __repr__(self):
        return f"GradNode({self.name})"


def _is_float_array(x) -> bool:
    """Differentiable dtypes: floating or complex (fft ops chain complex
    intermediates through the tape)."""
    try:
        dt = jnp.result_type(x)
        return (jnp.issubdtype(dt, jnp.floating)
                or jnp.issubdtype(dt, jnp.complexfloating))
    except TypeError:
        return False


def record_op(
    name: str,
    fn: Callable,
    tensor_args: Sequence[Any],
    values: Tuple[Any, ...],
    kwargs: Dict[str, Any],
):
    """Execute ``fn(*values, **kwargs)`` and, if recording, attach a GradNode.

    Returns (raw_outputs, node_or_None, out_is_tuple).
    ``tensor_args`` is parallel to ``values``: the Tensor object for args that
    were Tensors, else None.
    """
    from .tensor import Tensor  # local to avoid import cycle

    diff_idx = [
        i
        for i, t in enumerate(tensor_args)
        if t is not None and not t.stop_gradient and _is_float_array(values[i])
    ]
    if not (is_grad_enabled() and diff_idx):
        out = fn(*values, **kwargs)
        return out, None

    def closed(*dargs):
        vals = list(values)
        for i, v in zip(diff_idx, dargs):
            vals[i] = v
        return fn(*vals, **kwargs)

    primals = tuple(values[i] for i in diff_idx)
    out, vjp_fn = jax.vjp(closed, *primals)
    leaves = out if isinstance(out, (tuple, list)) else (out,)
    out_avals = [(np.shape(o), jnp.result_type(o)) for o in leaves]
    node = GradNode(name, vjp_fn, [tensor_args[i] for i in diff_idx], out_avals)
    return out, node


def backward(tensors, grad_tensors=None, retain_graph: bool = False) -> None:
    """``paddle.autograd.backward`` / ``Tensor.backward()``.

    Reverse-topological ready-queue over the recorded GradNode DAG —
    the same algorithm as the reference's egr::Backward
    (paddle/fluid/eager/backward.cc), in Python over jax VJPs.
    """
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # Seed cotangents.
    node_cotangents: Dict[GradNode, List[Optional[jax.Array]]] = {}
    roots: List[GradNode] = []

    def _seed(node: GradNode, idx: int, g):
        buf = node_cotangents.setdefault(node, [None] * len(node.out_avals))
        buf[idx] = g if buf[idx] is None else buf[idx] + g

    for t, g in zip(tensors, grad_tensors):
        if t._grad_node is None:
            # A leaf: backward() on it just sets its own grad.
            if not t.stop_gradient:
                seed = g.value if g is not None else jnp.ones_like(t.value)
                t._accumulate_grad(seed)
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}. Pass grad_tensors explicitly."
                )
            gval = jnp.ones_like(t.value)
        else:
            gval = g.value if isinstance(g, Tensor) else jnp.asarray(g)
        _seed(t._grad_node, t._out_index, gval)
        roots.append(t._grad_node)

    if not roots:
        return

    # Collect reachable nodes and count consumers of each producer node.
    reachable: set = set()
    stack = list(roots)
    while stack:
        n = stack.pop()
        if n in reachable:
            continue
        reachable.add(n)
        stack.extend(n.parents())

    consumer_count: Dict[GradNode, int] = {n: 0 for n in reachable}
    for n in reachable:
        for p in n.parents():
            consumer_count[p] += 1

    # Kahn init on the reversed DAG: start from nodes no reachable consumer
    # still needs (the loss-side frontier).
    pending = dict(consumer_count)
    ready = [n for n, c in pending.items() if c == 0]

    processed = set()
    while ready:
        node = ready.pop()
        if node in processed:
            continue
        processed.add(node)
        buf = node_cotangents.get(node)
        if buf is None:
            # No cotangent ever reached this node (dead branch): its inputs get
            # zeros only if someone needs them; skip entirely.
            cots = None
        else:
            cots = [
                c if c is not None else jnp.zeros(shape, dtype)
                for c, (shape, dtype) in zip(buf, node.out_avals)
            ]
        if cots is not None:
            out_struct = cots[0] if len(cots) == 1 else tuple(cots)
            # jax.vjp returns cotangent tuple for the diff inputs
            try:
                in_grads = node.vjp_fn(out_struct)
            except TypeError:
                in_grads = node.vjp_fn(tuple(cots))
            for t, gval in zip(node.inputs, in_grads):
                if gval is None:
                    continue
                if t._grad_node is not None:
                    _seed(t._grad_node, t._out_index, gval)
                if t._grad_node is None or t._retain_grads:
                    t._accumulate_grad(gval)
        if not retain_graph:
            node.vjp_fn = None
        node_cotangents.pop(node, None)
        for p in node.parents():
            pending[p] -= 1
            if pending[p] == 0:
                ready.append(p)


class PyLayerContext:
    """Context passed to PyLayer.forward/backward (paddle.autograd.PyLayer)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    """User-defined differentiable function, mirroring ``paddle.autograd.PyLayer``.

    Subclass with ``forward(ctx, *args)`` and ``backward(ctx, *grads)`` static
    methods; call via ``MyLayer.apply(*args)``. Under the hood the backward is
    registered on the tape as a custom vjp.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from .tensor import Tensor

        ctx = PyLayerContext()
        tensor_args = [a if isinstance(a, Tensor) else None for a in args]
        with no_grad_guard():
            out = cls.forward(ctx, *args, **kwargs)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        outs = [o for o in outs]

        diff_inputs = [
            t for t in tensor_args
            if t is not None and not t.stop_gradient and _is_float_array(t.value)
        ]
        if not (is_grad_enabled() and diff_inputs):
            return out

        out_avals = [(tuple(o.shape), o.dtype.np_dtype) for o in outs]

        diff_ids = {id(t) for t in diff_inputs}

        def vjp_fn(cotangent):
            cots = cotangent if isinstance(cotangent, tuple) else (cotangent,)
            cot_tensors = [Tensor(c, stop_gradient=True) for c in cots]
            with no_grad_guard():
                gin = cls.backward(ctx, *cot_tensors)
            gins = list(gin) if isinstance(gin, (tuple, list)) else [gin]
            # paddle semantics: backward returns one grad per differentiable
            # forward input, in order.
            vals = []
            gi = iter(gins)
            for t in args:
                if isinstance(t, Tensor) and id(t) in diff_ids:
                    g = next(gi, None)
                    vals.append(
                        None if g is None else (g.value if isinstance(g, Tensor) else g)
                    )
            return tuple(vals)

        node = GradNode(cls.__name__, vjp_fn, diff_inputs, out_avals)
        for i, o in enumerate(outs):
            o.stop_gradient = False
            o._grad_node = node
            o._out_index = i
        return out if isinstance(out, (tuple, list)) else outs[0]
