"""Error-reporting helpers.

TPU-native analogue of the reference's PADDLE_ENFORCE macro family
(paddle/fluid/platform/enforce.h, paddle/phi/core/enforce.h): typed error
classes with readable messages. Python stack traces replace the reference's
demangled C++ stacks; the error taxonomy mirrors paddle's error types so
user code catching them ports over.
"""

from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Base framework error (paddle's ``EnforceNotMet``)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


def enforce(cond: bool, msg: str = "", err_cls=EnforceNotMet) -> None:
    """PADDLE_ENFORCE analogue: raise ``err_cls`` when ``cond`` is false."""
    if not cond:
        raise err_cls(msg or "Enforce condition failed.")


def enforce_eq(a, b, msg: str = "") -> None:
    if a != b:
        raise InvalidArgumentError(f"{msg} (expected {a!r} == {b!r})")


def enforce_shape_eq(shape_a, shape_b, msg: str = "") -> None:
    if tuple(shape_a) != tuple(shape_b):
        raise InvalidArgumentError(
            f"{msg} (shape mismatch: {tuple(shape_a)} vs {tuple(shape_b)})"
        )


def not_implemented(what: str) -> None:
    raise UnimplementedError(
        f"{what} is not implemented in paddle_tpu. "
        "If this is load-bearing for your workload, file an issue."
    )
