// Native IO core for paddle_tpu — the C++ data-path the reference keeps in
// paddle/fluid/framework/data_feed* and the DataLoader C workers.
//
// TPU-native role: the accelerator consumes large host batches; the Python
// overhead that matters is index shuffling, per-sample gathering, and
// keeping the next batch ready while the chip runs. All three live here,
// off the GIL (ctypes releases it for the call duration; the prefetcher's
// producer runs on its own std::thread).
//
// C ABI only — bound via ctypes (no pybind11 in the image, by design).
//
//   ptio_shuffle        deterministic Fisher-Yates over an index array
//   ptio_gather         multithreaded fixed-size-record gather
//   ptio_prefetcher_*   background producer of shuffled, gathered batches
//                       into a bounded queue (epoch-based, reusable)

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// splitmix64: tiny, seedable, high-quality enough for shuffling
static inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

// unbiased bounded draw (Lemire)
static inline uint64_t bounded(uint64_t& state, uint64_t n) {
  uint64_t x = splitmix64(state);
  __uint128_t m = (__uint128_t)x * (__uint128_t)n;
  uint64_t l = (uint64_t)m;
  if (l < n) {
    uint64_t t = -n % n;
    while (l < t) {
      x = splitmix64(state);
      m = (__uint128_t)x * (__uint128_t)n;
      l = (uint64_t)m;
    }
  }
  return (uint64_t)(m >> 64);
}

void shuffle_indices(int64_t* idx, int64_t n, uint64_t seed) {
  uint64_t state = seed ^ 0xdeadbeefcafef00dull;
  for (int64_t i = n - 1; i > 0; --i) {
    uint64_t j = bounded(state, (uint64_t)(i + 1));
    int64_t tmp = idx[i];
    idx[i] = idx[j];
    idx[j] = tmp;
  }
}

void gather_records(const uint8_t* src, const int64_t* indices,
                    int64_t n_idx, int64_t record_bytes, uint8_t* dst,
                    int32_t n_threads) {
  if (n_threads <= 1 || n_idx < n_threads * 4) {
    for (int64_t i = 0; i < n_idx; ++i)
      std::memcpy(dst + i * record_bytes, src + indices[i] * record_bytes,
                  (size_t)record_bytes);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min(lo + chunk, n_idx);
    if (lo >= hi) break;
    threads.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i)
        std::memcpy(dst + i * record_bytes, src + indices[i] * record_bytes,
                    (size_t)record_bytes);
    });
  }
  for (auto& th : threads) th.join();
}

struct Batch {
  std::vector<std::vector<uint8_t>> bufs;  // one per array
  int64_t size = 0;                        // records in this batch
};

struct Prefetcher {
  // dataset: n_arrays parallel arrays sharing the leading dim
  std::vector<const uint8_t*> srcs;
  std::vector<int64_t> record_bytes;
  int64_t n_records = 0;
  int64_t batch_size = 0;
  bool drop_last = false;
  bool shuffle = false;
  int32_t capacity = 2;
  int32_t n_threads = 1;

  std::vector<int64_t> order;
  std::deque<Batch> queue;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::thread producer;
  std::atomic<bool> stop{false};
  bool epoch_done = true;  // producer finished current epoch

  void produce(uint64_t seed) {
    if (shuffle) shuffle_indices(order.data(), n_records, seed);
    int64_t pos = 0;
    while (pos < n_records && !stop.load(std::memory_order_relaxed)) {
      int64_t bs = std::min(batch_size, n_records - pos);
      if (bs < batch_size && drop_last) break;
      Batch b;
      b.size = bs;
      b.bufs.resize(srcs.size());
      for (size_t a = 0; a < srcs.size(); ++a) {
        b.bufs[a].resize((size_t)(bs * record_bytes[a]));
        gather_records(srcs[a], order.data() + pos, bs, record_bytes[a],
                       b.bufs[a].data(), n_threads);
      }
      pos += bs;
      std::unique_lock<std::mutex> lk(mu);
      cv_push.wait(lk, [&] {
        return (int32_t)queue.size() < capacity ||
               stop.load(std::memory_order_relaxed);
      });
      if (stop.load(std::memory_order_relaxed)) break;
      queue.push_back(std::move(b));
      cv_pop.notify_one();
    }
    // EVERY exit path must mark the epoch done and wake readers —
    // otherwise a reader blocked in ptio_prefetcher_next survives destroy
    // and wakes on a freed condvar
    std::lock_guard<std::mutex> lk(mu);
    epoch_done = true;
    cv_pop.notify_all();
  }
};

}  // namespace

extern "C" {

void ptio_shuffle(int64_t* idx, int64_t n, uint64_t seed) {
  shuffle_indices(idx, n, seed);
}

void ptio_gather(const uint8_t* src, const int64_t* indices, int64_t n_idx,
                 int64_t record_bytes, uint8_t* dst, int32_t n_threads) {
  gather_records(src, indices, n_idx, record_bytes, dst, n_threads);
}

void* ptio_prefetcher_create(const uint8_t** srcs,
                             const int64_t* record_bytes, int32_t n_arrays,
                             int64_t n_records, int64_t batch_size,
                             int32_t drop_last, int32_t shuffle,
                             int32_t capacity, int32_t n_threads) {
  if (n_arrays <= 0 || n_records <= 0 || batch_size <= 0) return nullptr;
  auto* p = new Prefetcher();
  p->srcs.assign(srcs, srcs + n_arrays);
  p->record_bytes.assign(record_bytes, record_bytes + n_arrays);
  p->n_records = n_records;
  p->batch_size = batch_size;
  p->drop_last = drop_last != 0;
  p->shuffle = shuffle != 0;
  p->capacity = capacity > 0 ? capacity : 2;
  p->n_threads = n_threads > 0 ? n_threads : 1;
  p->order.resize(n_records);
  for (int64_t i = 0; i < n_records; ++i) p->order[i] = i;
  return p;
}

// Begin one pass over the data: joins any previous epoch, clears the
// queue, reshuffles (when enabled) with epoch_seed, starts the producer.
void ptio_prefetcher_start_epoch(void* h, uint64_t epoch_seed) {
  auto* p = static_cast<Prefetcher*>(h);
  if (p->producer.joinable()) {
    p->stop.store(true);
    p->cv_push.notify_all();
    p->producer.join();
    p->stop.store(false);
  }
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->queue.clear();
    p->epoch_done = false;
  }
  p->producer = std::thread([p, epoch_seed] { p->produce(epoch_seed); });
}

// Copies the next batch into caller buffers (one per array, each at least
// batch_size * record_bytes[a]). Returns the record count, or 0 at epoch
// end, or -1 on error (no epoch started).
int64_t ptio_prefetcher_next(void* h, uint8_t** dsts) {
  auto* p = static_cast<Prefetcher*>(h);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_pop.wait(lk, [&] {
    return !p->queue.empty() || p->epoch_done ||
           p->stop.load(std::memory_order_relaxed);
  });
  if (p->queue.empty()) return 0;  // epoch done/stopped and drained
  Batch b = std::move(p->queue.front());
  p->queue.pop_front();
  p->cv_push.notify_one();
  lk.unlock();
  for (size_t a = 0; a < b.bufs.size(); ++a)
    std::memcpy(dsts[a], b.bufs[a].data(), b.bufs[a].size());
  return b.size;
}

void ptio_prefetcher_destroy(void* h) {
  auto* p = static_cast<Prefetcher*>(h);
  p->stop.store(true);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->cv_push.notify_all();
    p->cv_pop.notify_all();
  }
  if (p->producer.joinable()) p->producer.join();
  delete p;
}

}  // extern "C"
