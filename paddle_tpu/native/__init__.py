"""Native (C++) runtime components, bound via ctypes.

The reference implements its data path (DataFeed, DataLoader workers,
shared-memory queues) in C++; this package is the TPU-native equivalent:
``src/io_core.cpp`` compiles lazily with the system g++ into
``_io_core.so`` (cached next to the source, rebuilt when the source
changes). Everything degrades gracefully: if no compiler is available or
``PADDLE_TPU_DISABLE_NATIVE=1`` is set, callers fall back to the pure
NumPy path — same semantics, same RNG order is NOT guaranteed between the
two paths (document at call sites), but each path is deterministic.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["available", "shuffled_indices", "gather", "BatchPrefetcher"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "io_core.cpp")
_SO = os.path.join(_HERE, "_io_core.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False


def _build() -> bool:
    # per-pid temp + atomic rename: concurrent builders (pytest workers,
    # spawned trainers) must not corrupt each other's output
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("PADDLE_TPU_DISABLE_NATIVE") == "1":
            _load_failed = True
            return None
        stale = (not os.path.exists(_SO)
                 or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if stale and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _load_failed = True
            return None
        lib.ptio_shuffle.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_uint64]
        lib.ptio_gather.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32]
        lib.ptio_prefetcher_create.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
        lib.ptio_prefetcher_create.restype = ctypes.c_void_p
        lib.ptio_prefetcher_start_epoch.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64]
        lib.ptio_prefetcher_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
        lib.ptio_prefetcher_next.restype = ctypes.c_int64
        lib.ptio_prefetcher_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native core is built and loadable."""
    return _load() is not None


def shuffled_indices(n: int, seed: int) -> np.ndarray:
    """Deterministic permutation of [0, n). Native Fisher-Yates when
    available; NumPy fallback (different but equally deterministic order)."""
    lib = _load()
    if lib is None:
        return np.random.default_rng(seed).permutation(n).astype(np.int64)
    idx = np.arange(n, dtype=np.int64)
    lib.ptio_shuffle(idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                     n, ctypes.c_uint64(seed & (2**64 - 1)))
    return idx


def gather(src: np.ndarray, indices: np.ndarray,
           n_threads: int = 4) -> np.ndarray:
    """dst[i] = src[indices[i]] over the leading dim — multithreaded
    memcpy when native, ``src[indices]`` otherwise."""
    lib = _load()
    src = np.ascontiguousarray(src)
    if lib is None:
        return src[indices]
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    out = np.empty((len(indices),) + src.shape[1:], src.dtype)
    rec = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    lib.ptio_gather(
        src.ctypes.data_as(ctypes.c_void_p),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(indices), rec, out.ctypes.data_as(ctypes.c_void_p),
        n_threads)
    return out


class BatchPrefetcher:
    """Background batch producer over parallel arrays sharing dim 0.

    The C++ producer thread shuffles (per epoch), gathers records with a
    small thread pool, and keeps up to ``capacity`` batches queued while
    Python/the chip consume — the reference DataLoader's C-worker role.
    Iterate via ``epoch(seed)``; falls back to NumPy when native is
    unavailable.
    """

    def __init__(self, arrays: Sequence[np.ndarray], batch_size: int,
                 shuffle: bool = False, drop_last: bool = False,
                 capacity: int = 2, n_threads: int = 4):
        self.arrays = [np.ascontiguousarray(a) for a in arrays]
        n = len(self.arrays[0])
        if any(len(a) != n for a in self.arrays):
            raise ValueError("arrays must share dim 0")
        self.n = n
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._lib = _load()
        self._handle = None
        if self._lib is not None:
            ptrs = (ctypes.c_void_p * len(self.arrays))(
                *[a.ctypes.data_as(ctypes.c_void_p).value
                  for a in self.arrays])
            recs = (ctypes.c_int64 * len(self.arrays))(
                *[a.dtype.itemsize *
                  int(np.prod(a.shape[1:], dtype=np.int64))
                  for a in self.arrays])
            self._handle = self._lib.ptio_prefetcher_create(
                ptrs, recs, len(self.arrays), n, self.batch_size,
                int(drop_last), int(shuffle), capacity, n_threads)

    def __len__(self):
        if self.drop_last:
            return self.n // self.batch_size
        return (self.n + self.batch_size - 1) // self.batch_size

    def epoch(self, seed: int = 0):
        """Yield batches (tuple of np arrays, one per input array)."""
        if self._handle is None:
            yield from self._numpy_epoch(seed)
            return
        self._lib.ptio_prefetcher_start_epoch(
            self._handle, ctypes.c_uint64(seed & (2**64 - 1)))
        while True:
            outs = [np.empty((self.batch_size,) + a.shape[1:], a.dtype)
                    for a in self.arrays]
            ptrs = (ctypes.c_void_p * len(outs))(
                *[o.ctypes.data_as(ctypes.c_void_p).value for o in outs])
            got = self._lib.ptio_prefetcher_next(self._handle, ptrs)
            if got <= 0:
                return
            if got < self.batch_size:
                outs = [o[:got] for o in outs]
            yield tuple(outs)

    def _numpy_epoch(self, seed: int):
        order = (np.random.default_rng(seed).permutation(self.n)
                 if self.shuffle else np.arange(self.n))
        for lo in range(0, self.n, self.batch_size):
            idx = order[lo:lo + self.batch_size]
            if len(idx) < self.batch_size and self.drop_last:
                return
            yield tuple(a[idx] for a in self.arrays)

    def close(self):
        if self._handle is not None:
            self._lib.ptio_prefetcher_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
