"""Viterbi decode (reference: python/paddle/text/viterbi_decode.py —
the ViterbiDecodeOp CUDA kernel collapses into a lax.scan dynamic
program that jits onto TPU).

Conventions (PaddleNLP LinearChainCrf layout):
  - ``transitions[i, j]`` = score of moving FROM tag ``i`` TO tag ``j``.
  - With ``include_bos_eos_tag=True`` the last two tag indices are
    BOS = C-2 and EOS = C-1: the path score adds ``transitions[BOS, y0]``
    and ``transitions[y_last, EOS]``.
Path score = Σ_t potentials[t, y_t] + Σ_{t>0} transitions[y_{t-1}, y_t]
(+ BOS/EOS terms). ``lengths`` masks ragged batches: updates freeze past
each sequence's end, so the EOS term lands on the true last step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..nn.layer import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _viterbi(pot, trans, lengths, include_bos_eos_tag: bool):
    B, L, C = pot.shape
    lengths = lengths.astype(jnp.int32)
    alpha = pot[:, 0]
    if include_bos_eos_tag:
        alpha = alpha + trans[C - 2][None, :]
    ident = jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32), (B, C))

    def step(carry, inp):
        alpha = carry
        pot_t, t = inp
        m = alpha[:, :, None] + trans[None]          # (B, C_prev, C_next)
        best_prev = jnp.argmax(m, axis=1).astype(jnp.int32)
        new_alpha = jnp.max(m, axis=1) + pot_t
        live = (t < lengths)[:, None]
        alpha = jnp.where(live, new_alpha, alpha)
        bp = jnp.where(live, best_prev, ident)
        return alpha, bp

    ts = jnp.arange(1, L, dtype=jnp.int32)
    alpha, bps = lax.scan(step, alpha, (jnp.swapaxes(pot[:, 1:], 0, 1), ts))
    if include_bos_eos_tag:
        alpha = alpha + trans[:, C - 1][None, :]
    scores = jnp.max(alpha, axis=1)
    last = jnp.argmax(alpha, axis=1).astype(jnp.int32)

    def back(carry, bp):
        tag = carry
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # reverse scan: ys are stored at their input positions, so tags_rev[t]
    # is the tag at timestep t+1 and the final carry is the tag at t=0
    first, tags_rev = lax.scan(back, last, bps, reverse=True)
    paths = jnp.concatenate([first[None, :], tags_rev], axis=0)  # (L, B)
    paths = jnp.swapaxes(paths, 0, 1)                 # (B, L)
    valid = jnp.arange(L)[None, :] < lengths[:, None]
    # reference dtype is int64; jax without x64 stores int32 (same ids)
    paths = jnp.where(valid, paths, 0).astype(jnp.int32)
    return scores, paths


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """→ (scores (B,), paths (B, L) int64) — best tag sequences."""
    pot = _val(potentials).astype(jnp.float32)
    trans = _val(transition_params).astype(jnp.float32)
    lens = _val(lengths)
    scores, paths = _viterbi(pot, trans, lens, include_bos_eos_tag)
    return (Tensor(scores, stop_gradient=True),
            Tensor(paths, stop_gradient=True))


class ViterbiDecoder(Layer):
    """reference class of the same name: holds ``transitions``, decodes
    in ``forward(potentials, lengths)``."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = (transitions if isinstance(transitions, Tensor)
                            else Tensor(jnp.asarray(transitions),
                                        stop_gradient=True))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
