"""paddle.text — NLP datasets + Viterbi decode.

Reference: python/paddle/text/__init__.py (dataset wrappers around
downloaded corpora) and python/paddle/text/viterbi_decode.py.

The decode op is real (lax.scan dynamic program, jit-friendly). The
corpus datasets require downloads this zero-egress environment cannot
perform; they raise with guidance instead of silently returning empty
data — pass the reference-format local files where supported.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..io.dataset import Dataset
from .viterbi_decode import ViterbiDecoder, viterbi_decode

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16",
           "ViterbiDecoder", "viterbi_decode"]


class _DownloadDataset(Dataset):
    """Shared guard: the reference downloads these corpora on first use;
    there is no egress here, so constructing without a local file is an
    immediate, explicit error (never an empty dataset)."""

    NAME = "corpus"
    FORMAT = "the reference's archive format"

    def __init__(self, data_file: Optional[str] = None, **kw):
        if data_file is None:
            raise ValueError(
                f"paddle.text.{type(self).__name__}: automatic download is "
                f"unsupported (no network egress). Obtain {self.NAME} "
                f"({self.FORMAT}) out of band and pass "
                f"data_file=<local path>.")
        self.data_file = data_file
        self._load(data_file, **kw)

    def _load(self, data_file: str, **kw):
        raise NotImplementedError(
            f"paddle.text.{type(self).__name__}: local parsing for "
            f"{self.FORMAT} is not implemented in this build; read the "
            f"file with your own loader and wrap it in an io.Dataset")

    def __getitem__(self, idx):
        return self._items[idx]

    def __len__(self):
        return len(self._items)


class Imdb(_DownloadDataset):
    NAME = "the IMDB movie-review sentiment corpus"
    FORMAT = "aclImdb_v1.tar.gz"


class Imikolov(_DownloadDataset):
    NAME = "the Mikolov PTB language-model corpus"
    FORMAT = "simple-examples.tgz"


class Movielens(_DownloadDataset):
    NAME = "the MovieLens-1M ratings corpus"
    FORMAT = "ml-1m.zip"


class WMT14(_DownloadDataset):
    NAME = "the WMT'14 EN-FR translation corpus"
    FORMAT = "wmt14.tgz"


class WMT16(_DownloadDataset):
    NAME = "the WMT'16 EN-DE translation corpus"
    FORMAT = "wmt16.tar.gz"


class UCIHousing(_DownloadDataset):
    """Boston-housing regression rows; the local file is the plain
    whitespace-separated 14-column table the reference downloads, so
    local parsing IS implemented."""

    NAME = "the UCI housing table"
    FORMAT = "housing.data (14 whitespace-separated columns)"

    def _load(self, data_file: str, mode: str = "train"):
        raw = np.loadtxt(data_file).astype(np.float32)
        if raw.ndim != 2 or raw.shape[1] != 14:
            raise ValueError(
                f"expected 14 columns (13 features + target), got "
                f"{raw.shape}")
        # reference normalization: feature-wise max/min scaling over the
        # whole table, then an 80/20 train/test split
        feats, target = raw[:, :13], raw[:, 13:]
        lo, hi = feats.min(0), feats.max(0)
        feats = (feats - lo) / np.maximum(hi - lo, 1e-12)
        n_train = int(raw.shape[0] * 0.8)
        sl = slice(0, n_train) if mode == "train" else slice(n_train, None)
        self._items = [(feats[i], target[i])
                       for i in range(*sl.indices(raw.shape[0]))]
