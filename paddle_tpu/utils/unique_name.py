"""reference: paddle.utils.unique_name (python/paddle/utils/unique_name.py)."""

from __future__ import annotations

import contextlib
import threading

_lock = threading.Lock()
_counters = {}


def generate(key: str = "tmp") -> str:
    with _lock:
        n = _counters.get(key, 0)
        _counters[key] = n + 1
    return f"{key}_{n}"


@contextlib.contextmanager
def guard(new_generator=None):
    global _counters
    with _lock:
        saved = dict(_counters)
        _counters = {}
    try:
        yield
    finally:
        with _lock:
            _counters = saved


def switch(new_namespace=None):
    global _counters
    old = dict(_counters)
    _counters = {}
    return old
