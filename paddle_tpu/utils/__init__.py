from . import install_check  # noqa: F401
from .install_check import run_check  # noqa: F401


def try_import(module_name: str):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            f"{module_name} is required but not installed (offline image: "
            "no pip installs available).") from e


def unique_name_generator(prefix: str = "tmp"):
    import itertools
    counter = itertools.count()

    def gen():
        return f"{prefix}_{next(counter)}"

    return gen
