from . import install_check  # noqa: F401
from .install_check import run_check  # noqa: F401


def try_import(module_name: str):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            f"{module_name} is required but not installed (offline image: "
            "no pip installs available).") from e


def unique_name_generator(prefix: str = "tmp"):
    import itertools
    counter = itertools.count()

    def gen():
        return f"{prefix}_{next(counter)}"

    return gen


def deprecated(update_to="", since="", reason="", level=0):
    """reference: paddle.utils.deprecated decorator."""
    import functools
    import warnings

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*a, **k):
            warnings.warn(
                f"{fn.__name__} is deprecated since {since or 'n/a'}"
                + (f", use {update_to}" if update_to else "")
                + (f" ({reason})" if reason else ""),
                DeprecationWarning, stacklevel=2)
            return fn(*a, **k)
        return inner
    return wrap


def require_version(min_version: str, max_version: str = None):
    """reference: paddle.utils.require_version — checked against this
    build's version string."""
    from ..version import full_version

    def key(v):
        return tuple(int(x) for x in str(v).split(".")[:3])
    if key(full_version) < key(min_version):
        raise RuntimeError(
            f"requires paddle >= {min_version}, found {full_version}")
    if max_version is not None and key(full_version) > key(max_version):
        raise RuntimeError(
            f"requires paddle <= {max_version}, found {full_version}")
    return True


def get_weights_path_from_url(url: str, md5sum=None) -> str:
    """reference: paddle.utils.download.get_weights_path_from_url. This
    deployment has no network egress: the file must already sit in the
    cache dir (~/.cache/paddle/weights); otherwise a clear error tells
    the operator to place it there."""
    import os
    cache = os.path.expanduser("~/.cache/paddle/weights")
    fname = url.split("/")[-1]
    path = os.path.join(cache, fname)
    if os.path.isfile(path):
        return path
    raise FileNotFoundError(
        f"no network egress to fetch {url!r}; place the file at {path}")


from . import cpp_extension  # noqa: E402,F401
from . import dlpack  # noqa: E402,F401
from . import unique_name  # noqa: E402,F401
