"""Install sanity check (reference: python/paddle/utils/install_check.py)."""

from __future__ import annotations

import numpy as np


def run_check() -> None:
    """``paddle.utils.run_check`` analogue: verifies device visibility, a
    compiled matmul on the default device, and (if >1 device) a psum across
    all devices."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as ptpu

    devices = jax.devices()
    print(f"paddle_tpu {ptpu.__version__} is installed; "
          f"found {len(devices)} device(s): {[str(d) for d in devices]}")

    from paddle_tpu.generation.program_cache import \
        clear_decode_program_cache

    x = ptpu.randn([128, 128], dtype="float32")
    # correctness probe at full precision (the MXU's default bf16-accumulated
    # path is intentionally inexact vs numpy); tpu_matmul_precision rides
    # compiled serving programs (PROGRAM_FLAGS), so re-arm the program
    # cache around the flag flip
    ptpu.set_flags({"tpu_matmul_precision": "highest"})
    clear_decode_program_cache()
    try:
        y = ptpu.matmul(x, x)
        assert tuple(y.shape) == (128, 128)
        np.testing.assert_allclose(
            y.numpy(), np.asarray(x._value) @ np.asarray(x._value),
            rtol=1e-3, atol=1e-3)
    finally:
        ptpu.set_flags({"tpu_matmul_precision": "default"})
        clear_decode_program_cache()
    print("paddle_tpu single-device matmul: OK")

    if len(devices) > 1:
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        mesh = Mesh(np.array(devices), axis_names=("x",))
        f = shard_map(lambda a: jax.lax.psum(a, "x"), mesh=mesh,
                      in_specs=P("x"), out_specs=P())
        out = f(jnp.ones((len(devices), 8)))
        assert float(out.ravel()[0]) == float(len(devices))
        print(f"paddle_tpu {len(devices)}-device collective (psum): OK")
    print("paddle_tpu is installed successfully!")
