"""reference: paddle.utils.dlpack — zero-copy tensor exchange. The
modern dlpack protocol passes an OBJECT exposing __dlpack__ /
__dlpack_device__ (not a raw capsule); jax arrays implement it, so
``to_dlpack`` hands out the underlying array and ``from_dlpack``
accepts anything protocol-compliant (numpy/torch/jax arrays)."""

from __future__ import annotations

from ..core.tensor import Tensor, _val


def to_dlpack(x):
    return _val(x)


def from_dlpack(ext):
    import jax.numpy as jnp
    return Tensor(jnp.from_dlpack(ext), stop_gradient=True)
