"""Custom C++ op runtime (reference: python/paddle/utils/cpp_extension/ +
paddle/phi/api/ext/ OpMetaInfo).

``load(name, sources)`` JIT-compiles user C++ into a shared library and
returns a module of Python ops. The TPU-native twist: custom C++ runs on
the HOST, so inside ``jit`` the op executes via ``jax.pure_callback`` —
XLA calls back to the host mid-program, the same role the reference's
custom-op registry plays for CPU kernels. Eagerly it's a direct ctypes
call. Autograd: pass ``backward_for(...)`` to register a VJP.

User C ABI (one function per op):

    extern "C" void my_op(const float* x, float* out, int64_t n);

declared to ``load`` via ``functions={"my_op": spec}`` where spec lists
the argument roles — see ``FunctionSpec``.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, _val

__all__ = ["load", "CppExtension", "FunctionSpec", "get_build_directory"]

_DEFAULT_BUILD_DIR = os.path.join(
    tempfile.gettempdir(), "paddle_tpu_extensions")
_build_lock = threading.Lock()


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR", _DEFAULT_BUILD_DIR)
    os.makedirs(d, exist_ok=True)
    return d


@dataclass
class FunctionSpec:
    """Describes one exported C function.

    The C function receives, in order: one ``const T*`` per input, one
    ``T*`` per output, then one ``int64_t`` per dimension of each input's
    shape (flattened, inputs in order). Outputs are allocated by the
    caller with shapes from ``out_shapes(*input_shapes)`` (defaults to
    the first input's shape) and dtypes from ``out_dtypes``.
    """

    n_inputs: int = 1
    n_outputs: int = 1
    dtype: str = "float32"
    out_dtypes: Optional[Sequence[str]] = None
    out_shapes: Optional[Callable] = None  # (*in_shapes) -> [shape, ...]

    def resolve_out(self, in_shapes):
        shapes = (self.out_shapes(*in_shapes) if self.out_shapes
                  else [in_shapes[0]] * self.n_outputs)
        dtypes = list(self.out_dtypes or [self.dtype] * self.n_outputs)
        return [tuple(int(d) for d in s) for s in shapes], dtypes


_C_DTYPES = {
    "float32": ctypes.c_float, "float64": ctypes.c_double,
    "int32": ctypes.c_int32, "int64": ctypes.c_int64,
}


class _NativeFunction:
    def __init__(self, cfunc, name: str, spec: FunctionSpec):
        self._cfunc = cfunc
        self._name = name
        self._spec = spec
        self._vjp: Optional[Callable] = None

    def _host_call(self, *arrays):
        spec = self._spec
        want = np.dtype(spec.dtype)
        arrays = [np.ascontiguousarray(a, dtype=want) for a in arrays]
        in_shapes = [a.shape for a in arrays]
        out_shapes, out_dtypes = spec.resolve_out(in_shapes)
        outs = [np.zeros(s, d) for s, d in zip(out_shapes, out_dtypes)]
        args = []
        for a in arrays:
            args.append(a.ctypes.data_as(
                ctypes.POINTER(_C_DTYPES[str(a.dtype)])))
        for o in outs:
            args.append(o.ctypes.data_as(
                ctypes.POINTER(_C_DTYPES[str(o.dtype)])))
        for a in arrays:
            args.extend(ctypes.c_int64(d) for d in a.shape)
        self._cfunc(*args)
        return tuple(outs) if len(outs) != 1 else outs[0]

    def __call__(self, *tensors):
        spec = self._spec

        if self._spec.dtype not in _C_DTYPES or any(
                d not in _C_DTYPES for d in (self._spec.out_dtypes or [])):
            raise TypeError(
                f"custom op {self._name!r}: supported dtypes are "
                f"{sorted(_C_DTYPES)}")

        def fn(*vals):
            in_shapes = [np.shape(v) for v in vals]
            out_shapes, out_dtypes = spec.resolve_out(in_shapes)
            result_shape = [
                jax.ShapeDtypeStruct(s, jnp.dtype(d))
                for s, d in zip(out_shapes, out_dtypes)]
            if len(result_shape) == 1:
                result_shape = result_shape[0]
            # host callback: works eagerly AND inside jit-compiled
            # programs (XLA inserts a host transfer + callback)
            out = jax.pure_callback(self._host_call, result_shape, *vals,
                                    vmap_method="sequential")
            return out

        if self._vjp is not None:
            vjp = self._vjp
            inner = fn

            @jax.custom_vjp
            def fn_vjp(*vals):
                return inner(*vals)

            def fwd(*vals):
                return inner(*vals), vals

            def bwd(res, g):
                grads = vjp(res, g)
                return tuple(grads)
            fn_vjp.defvjp(fwd, bwd)
            fn = fn_vjp
        return apply_op(f"custom_op::{self._name}", fn, *tensors)

    def backward_for(self, grad_fn: Callable):
        """Register the VJP: ``grad_fn(saved_inputs, out_cotangent) ->
        tuple of input cotangents`` (jax-traceable)."""
        self._vjp = grad_fn
        return self


class CppExtension:
    """The loaded module: exported functions become attributes."""

    def __init__(self, name: str, lib, functions: Dict[str, FunctionSpec]):
        self.name = name
        self._lib = lib
        for fname, spec in functions.items():
            cfunc = getattr(lib, fname)
            cfunc.restype = None
            setattr(self, fname, _NativeFunction(cfunc, fname, spec))


def _compile(name: str, sources: List[str], extra_cxx_flags,
             build_dir: str) -> str:
    srcs = []
    for s in sources:
        if os.path.exists(s):
            with open(s) as f:
                srcs.append(f.read())
        elif "\n" not in s and "{" not in s and not any(
                c.isspace() for c in s):
            # a single path-like token that doesn't exist: typo'd filename
            raise FileNotFoundError(f"cpp_extension source not found: {s!r}")
        else:  # inline source string
            srcs.append(s)
    blob = "\n".join(srcs)
    tag = hashlib.sha256(
        (blob + " ".join(extra_cxx_flags)).encode()).hexdigest()[:16]
    so = os.path.join(build_dir, f"{name}_{tag}.so")
    if os.path.exists(so):
        return so
    src_path = os.path.join(build_dir, f"{name}_{tag}.cpp")
    with open(src_path, "w") as f:
        f.write(blob)
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           *extra_cxx_flags, src_path, "-o", tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if r.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{r.stderr[-2000:]}")
        os.replace(tmp, so)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return so


def load(name: str, sources: List[str],
         functions: Dict[str, FunctionSpec] = None,
         extra_cxx_flags: Sequence[str] = (),
         build_directory: Optional[str] = None,
         verbose: bool = False) -> CppExtension:
    """Compile + load a custom C++ op library (reference:
    paddle.utils.cpp_extension.load). ``sources`` are file paths or
    inline source strings; ``functions`` maps exported symbol ->
    FunctionSpec."""
    if not functions:
        raise ValueError(
            "functions={'symbol': FunctionSpec(...)} is required — the "
            "TPU build binds C symbols via ctypes, not op registration "
            "macros")
    build_dir = build_directory or get_build_directory()
    with _build_lock:
        so = _compile(name, list(sources), list(extra_cxx_flags), build_dir)
    lib = ctypes.CDLL(so)
    return CppExtension(name, lib, functions)


def CUDAExtension(sources, *args, **kwargs):
    """reference: cpp_extension.CUDAExtension — no CUDA toolchain on the
    TPU image; build the op as a plain C++ extension (CppExtension) or a
    Pallas kernel instead."""
    raise RuntimeError(
        "CUDAExtension: no CUDA toolchain in the TPU deployment; use "
        "CppExtension (host ops) or a Pallas kernel (device ops)")


def setup(**kwargs):
    """reference: cpp_extension.setup — setuptools driver for custom-op
    wheels. Delegates to setuptools with the C++ extension(s)."""
    from setuptools import setup as _setup
    ext = kwargs.pop("ext_modules", None)
    return _setup(ext_modules=ext if isinstance(ext, list) else
                  [ext] if ext else [], **kwargs)
