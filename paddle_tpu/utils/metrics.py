"""Training throughput metrics — tokens/sec/chip and MFU.

The north-star metric (BASELINE.md): first-class, not an afterthought.
MFU = achieved_flops / peak_flops with achieved ≈ 6N per token (dense
decoder fwd+bwd) plus the attention term 12·L·h·s per token.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import List, Optional

import jax

# peak bf16 FLOP/s per chip, from public TPU specs
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "cpu": 1e11,  # nominal, so MFU numbers exist in CPU sims
}


def detect_peak_flops() -> float:
    try:
        d = jax.devices()[0]
        kind = (getattr(d, "device_kind", "") or "").lower().replace(" ", "")
        for k, v in PEAK_FLOPS.items():
            if k in kind:
                return v
        if d.platform.lower() in ("tpu", "axon"):
            return PEAK_FLOPS["v5e"]
    except Exception:
        pass
    return PEAK_FLOPS["cpu"]


def train_flops_per_token(n_params: int, n_layers: int = 0, hidden: int = 0,
                          seq_len: int = 0) -> float:
    """6N + attention correction 12·L·h·s (fwd+bwd, dense decoder)."""
    flops = 6.0 * n_params
    if n_layers and hidden and seq_len:
        flops += 12.0 * n_layers * hidden * seq_len
    return flops


@dataclass
class SpeedMeter:
    """Step-time tracker producing tokens/sec/chip + MFU.

    Call ``start()`` then ``step(n_tokens)`` after each synchronized train
    step. Warmup steps are excluded from the medians (compile time).
    """

    n_params: int
    n_layers: int = 0
    hidden: int = 0
    seq_len: int = 0
    n_chips: int = 1
    warmup: int = 2
    peak_flops: float = 0.0
    times: List[float] = field(default_factory=list)
    tokens: List[int] = field(default_factory=list)
    _t0: Optional[float] = None

    def __post_init__(self):
        if not self.peak_flops:
            self.peak_flops = detect_peak_flops()

    def start(self):
        self._t0 = time.perf_counter()

    def step(self, n_tokens: int):
        now = time.perf_counter()
        if self._t0 is not None:
            self.times.append(now - self._t0)
            self.tokens.append(n_tokens)
        self._t0 = now

    def _steady(self):
        return self.times[self.warmup:] if len(self.times) > self.warmup else self.times

    def step_time(self) -> float:
        import numpy as np
        s = self._steady()
        return float(np.median(s)) if s else float("nan")

    def tokens_per_sec_per_chip(self) -> float:
        s = self._steady()
        tk = self.tokens[self.warmup:] if len(self.tokens) > self.warmup else self.tokens
        if not s:
            return 0.0
        return (sum(tk) / sum(s)) / max(self.n_chips, 1)

    def mfu(self) -> float:
        tps = self.tokens_per_sec_per_chip()
        fpt = train_flops_per_token(self.n_params, self.n_layers, self.hidden,
                                    self.seq_len)
        return tps * fpt / self.peak_flops

    def summary(self) -> dict:
        return {
            "median_step_time_s": self.step_time(),
            "tokens_per_sec_per_chip": self.tokens_per_sec_per_chip(),
            "mfu": self.mfu(),
            "n_chips": self.n_chips,
            "n_params": self.n_params,
            "peak_flops": self.peak_flops,
        }

    def log_line(self) -> str:
        return json.dumps(self.summary())
