"""Runtime flag registry.

TPU-native equivalent of the reference's gflags-style registry
(paddle/phi/core/flags.cc, paddle/utils/flags.h): typed, documented,
env-overridable flags, settable at runtime via ``set_flags`` and readable
via ``get_flags`` — same user API as ``paddle.set_flags``.

Flags are read from the environment (``FLAGS_<name>=...``) at first access,
so launchers can configure workers without code changes.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}


@dataclass
class _Flag:
    name: str
    default: Any
    type: type
    help: str
    value: Any = None
    is_set: bool = False  # explicitly set (env or set_flags)

    def current(self) -> Any:
        if self.is_set:
            return self.value
        env = os.environ.get("FLAGS_" + self.name)
        if env is not None:
            return _PARSERS[self.type](env)
        return self.default


class _Registry:
    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}
        self._lock = threading.Lock()

    def define(self, name: str, default: Any, help: str = "") -> None:
        with self._lock:
            if name in self._flags:
                return
            self._flags[name] = _Flag(name, default, type(default), help)

    def get(self, name: str) -> Any:
        f = self._flags.get(self._norm(name))
        if f is None:
            raise KeyError(f"Unknown flag: {name!r}. See paddle_tpu.flags.list_flags().")
        return f.current()

    def set(self, name: str, value: Any) -> None:
        key = self._norm(name)
        f = self._flags.get(key)
        if f is None:
            raise KeyError(f"Unknown flag: {name!r}. See paddle_tpu.flags.list_flags().")
        if isinstance(value, str) and f.type is not str:
            value = _PARSERS[f.type](value)
        f.value = f.type(value)
        f.is_set = True

    @staticmethod
    def _norm(name: str) -> str:
        return name[6:] if name.startswith("FLAGS_") else name

    def snapshot(self, names=None) -> "FlagSnapshot":
        """Resolve ``names`` (all flags when None) ONCE: one lock
        acquisition and one env read per flag, returning an immutable
        view. Hot paths (kernel dispatch) read the snapshot instead of
        hitting the registry per call."""
        with self._lock:
            if names is None:
                flags = list(self._flags.values())
            else:
                flags = [self._flags[self._norm(n)] for n in names]
        return FlagSnapshot({f.name: f.current() for f in flags})

    def all(self) -> Dict[str, Any]:
        return {n: f.current() for n, f in sorted(self._flags.items())}

    def describe(self) -> List[str]:
        return [
            f"FLAGS_{n} (default={f.default!r}): {f.help}"
            for n, f in sorted(self._flags.items())
        ]


class FlagSnapshot:
    """Immutable point-in-time flag view with mapping and attribute
    access. Kernels resolve ONE snapshot per trace (`flags.snapshot`)
    and thread it through their helpers instead of re-importing the
    registry and re-parsing the environment on every call — the decode
    hot path dispatches thousands of kernel calls per second and the
    per-call registry/env round-trips were measurable host overhead."""

    __slots__ = ("_values",)

    def __init__(self, values: Dict[str, Any]):
        object.__setattr__(self, "_values", dict(values))

    def __getattr__(self, name: str) -> Any:
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(f"flag {name!r} not in snapshot "
                                 f"(have {sorted(self._values)})") from None

    def __getitem__(self, name: str) -> Any:
        return self._values[name[6:] if name.startswith("FLAGS_") else name]

    def __contains__(self, name: str) -> bool:
        return (name[6:] if name.startswith("FLAGS_") else name) in self._values

    def __setattr__(self, name, value):
        raise TypeError("FlagSnapshot is immutable")

    def as_tuple(self) -> tuple:
        """Hashable (name, value) tuple — the ``flag tuple`` component of
        decode program cache keys."""
        return tuple(sorted(self._values.items()))

    def __repr__(self) -> str:
        return f"FlagSnapshot({self._values!r})"


_registry = _Registry()
define_flag = _registry.define


def snapshot(names=None) -> FlagSnapshot:
    """Resolve a set of flags once into an immutable :class:`FlagSnapshot`.
    ``names`` may be any iterable of flag names (with or without the
    ``FLAGS_`` prefix); None snapshots every registered flag."""
    return _registry.snapshot(names)


def set_flags(flags: Dict[str, Any]) -> None:
    """Set runtime flags. Mirrors ``paddle.set_flags``."""
    for k, v in flags.items():
        _registry.set(k, v)


def get_flags(names) -> Dict[str, Any]:
    """Read runtime flags. Mirrors ``paddle.get_flags``."""
    if isinstance(names, str):
        names = [names]
    out = {}
    for n in names:
        key = n if n.startswith("FLAGS_") else "FLAGS_" + n
        out[key] = _registry.get(n)
    return out


def get_flag(name: str) -> Any:
    return _registry.get(name)


def list_flags() -> List[str]:
    return _registry.describe()


# ---------------------------------------------------------------------------
# Core flag definitions (load-bearing set mirrored from the reference's
# paddle/phi/core/flags.cc; TPU-specific ones added).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False, "Check every op output for NaN/Inf (debug).")
define_flag("check_nan_inf_level", 0, "0: abort on nan/inf; >=1: report only.")
define_flag("benchmark", False, "Synchronize after each op and log timings.")
define_flag("deterministic", False, "Force deterministic kernels where possible.")
define_flag("use_pallas", True, "Use Pallas fused kernels where available (vs pure-XLA fallbacks).")
define_flag("flash_attn_min_seqlen", 1024,
            "Dispatch sdpa to the Pallas flash kernel only at seq >= this; "
            "0 = always flash. Lowered 2048 -> 1024 on r05 on-chip "
            "evidence: (a) ATTN_BENCH_r05 block sweep: 512x512 blocks cut "
            "flash fwd+bwd 108.6 -> 76.0 ms at seq 4096 (dense: 100.6), "
            "and flash already matched dense at 1024 with the OLD slow "
            "blocks; (b) PROFILE_r05: the dense path's materialized mask "
            "+ f32 score temps put copy/layout at 67% of accumulated "
            "device time on GPT-345M seq 1024; (c) TRAIN_TUNE_r05: dense "
            "bf16[16,16,1024,1024] score temps (512 MB/layer) OOM the "
            "batch-16 345M step that flash runs fine.")
define_flag("embedding_matmul_grad", "auto",
            "Embedding-lookup weight gradient as a one-hot matmul "
            "instead of jnp.take's scatter-add vjp: 'auto' = on TPU "
            "backends only (XLA lowers big scatter-adds to serialized "
            "while loops there — PROFILE_r05 top ops), 'on'/'off' = "
            "force. The matmul accumulates in f32 on the MXU; the "
            "transient one-hot is [tokens, vocab] in the grad dtype.")
define_flag("flash_compact_stats", True,
            "Flash-attention stats stay compact (BH, S) at the kernel "
            "boundary: fwd keeps softmax stats in VMEM scratch and emits "
            "lse via an in-kernel (1, bq) write; bwd loads lse/delta/seg "
            "as (1, bq) lane rows transposed in-kernel — kills the "
            "128x-replicated HBM transients (advisor r2). Default off "
            "until tools/chip_sprint.py validates the Mosaic layouts "
            "compile on a real chip; numerics are parity-tested in "
            "interpret mode either way.")
define_flag("flash_block_q", 512,
            "Flash-attention q rows per pallas grid step. Default 512: "
            "the r05 on-chip sweep (ATTN_BENCH_r05.json) measured "
            "512x512 at 76.0 ms vs 108.6 ms for the old 128x128 default "
            "(seq 4096 fwd+bwd, v5e) — fewer grid steps amortize the "
            "revisited-accumulator loads. Short sequences snap down "
            "automatically; set FLAGS_flash_block_q/_k (or pass "
            "block_q/block_k) to apply a different tuning.")
define_flag("flash_block_k", 512,
            "Flash-attention kv columns per pallas grid step (see "
            "flash_block_q).")
define_flag("fused_block_decode", True,
            "Serve steady-state decode through the fused transformer-block "
            "kernel (kernels/fused_block_decode.py): one program per layer "
            "computes rms_norm -> QKV -> RoPE -> paged attention -> "
            "out-proj -> rms_norm -> SwiGLU FFN with the per-slot "
            "activations VMEM-resident, instead of the op chain that "
            "round-trips HBM between every op. Applies to models exposing "
            "block_decode_spec() (the Llama family); others keep the "
            "generic compiled step. Env-overridable "
            "(FLAGS_fused_block_decode=0) like the flash block flags.")
define_flag("fused_block_layers", 1,
            "How many transformer blocks one fused decode kernel runs "
            "(kernels/fused_block_decode.py multi-layer mode): N > 1 "
            "groups the model's layers into ceil(L/N) stacked-weight "
            "groups, each dispatched as ONE pallas_call whose activations "
            "stay VMEM-resident across the group's layers and whose "
            "q/k/v and gate/up projections run as merged wider matmuls. "
            "1 (default) keeps the r06 one-kernel-per-layer step. Price "
            "an N before flipping it: "
            "`python tools/memwatch.py plan --fused-layers N` refuses an "
            "N whose VMEM working set cannot fit. Requires the model's "
            "block_decode_spec() to publish layer_groups; models that "
            "fall back to the generic step ignore this flag.")
define_flag("flash_dispatch_table", "0:flash;2048:dense;4096:512x512",
            "Per-shape flash-attention dispatch table: ';'-separated "
            "'<min_seqlen>:<entry>' buckets, entry one of 'flash' (kernel "
            "with the FLAGS_flash_block_{q,k} defaults), 'dense' (XLA "
            "dense sdpa), or 'BQxBK' (kernel with those blocks). A query "
            "length resolves to the bucket with the largest min_seqlen "
            "<= it; lengths below every bucket use 'flash'. Seeded from "
            "the r05 on-chip A/B (ATTN_BENCH_r05.json): flash matches "
            "dense at 1024 (1.01x), LOSES at 2048 (0.86x -> dense "
            "fallback so the fused path never loses to XLA dense), and "
            "wins at 4096+ with the 512x512 sweep blocks (76.0 ms vs "
            "100.6 dense). Applies where sdpa already cleared "
            "FLAGS_flash_attn_min_seqlen; set to '' to disable the table "
            "(always flash with the default blocks).")
define_flag("train_max_in_flight", 32,
            "Hard cap on dispatched-but-unsynced train steps. The async "
            "TrainStep window never blocks on the loss; this bound is the "
            "HBM safety net for callers that never pull metrics (each "
            "in-flight step holds its input batch buffers until it "
            "retires). Normal loops sync far earlier via "
            "metrics_every/sync().")
define_flag("allocator_strategy", "auto_growth", "Kept for API parity; PJRT owns memory on TPU.")
define_flag("fraction_of_gpu_memory_to_use", 0.92, "API parity; PJRT owns memory on TPU.")
define_flag("log_level", 1, "Framework log verbosity (GLOG_v analogue).")
define_flag("eager_delete_tensor_gb", 0.0, "API parity; JAX GC owns tensor lifetime.")
define_flag("tpu_matmul_precision", "default", "jax matmul precision: default|high|highest.")
define_flag("telemetry", True,
            "Host-side runtime telemetry (paddle_tpu.observability): the "
            "process-wide metrics registry and span tracer. Eager-only by "
            "design — telemetry never executes under trace and is NOT part "
            "of PROGRAM_FLAGS, so toggling it can never recompile a serving "
            "or train program. Off = instrumented code binds no-op stubs at "
            "construction time (zero registry lookups on hot paths).")
define_flag("memwatch", True,
            "Compiled-program memory capture (observability.memory): "
            "every program admitted by the decode program cache and "
            "every jitted TrainStep banks its XLA CompiledMemoryStats "
            "(argument/output/temp/alias/code bytes) as "
            "program_memory_bytes gauges + the memwatch program table. "
            "Capture costs ONE duplicate lower()+compile() per "
            "(re)trace — charged at the same moment r09's compile-time "
            "histogram already bills — and nothing per steady-state "
            "step. Rides the FLAGS_telemetry gate (telemetry off = "
            "memwatch off). Eager-only by design, NOT in PROGRAM_FLAGS: "
            "toggling never recompiles a serving or train program.")
define_flag("telemetry_ring", 16384,
            "Span-tracer ring-buffer capacity in events; the oldest events "
            "drop first, so a long-lived server keeps a bounded, recent "
            "timeline window.")
define_flag("embedding_deterministic", 0, "API parity with reference embedding determinism flag.")
define_flag("cudnn_deterministic", False, "API parity alias of FLAGS_deterministic.")
define_flag("fault_inject", "",
            "Deterministic fault-injection spec (paddle_tpu.testing."
            "faults): ';'-separated '<site>:every=N' / '<site>:p=F"
            "[:seed=N][:times=N][:after=N]' entries arming named "
            "injection sites (prefill, decode_dispatch, preempt, "
            "kv_spill, router_dispatch, spec_draft, spec_verify, "
            "program_build, train_dispatch, "
            "train_sync, dataloader_worker, "
            "checkpoint_save). Empty (default) = disabled: components "
            "bind no-op stubs at construction, zero hot-path cost. "
            "Eager-only by design — injection never changes a traced "
            "program, so it is NOT part of PROGRAM_FLAGS.")
define_flag("serving_max_retries", 3,
            "ServingEngine replay-recovery budget: how many consecutive "
            "NO-PROGRESS replays a request survives before it is "
            "terminated FAILED. A replay after new tokens were emitted "
            "resets the count — the budget guards wedged requests, not "
            "long ones under a flaky backend.")
define_flag("serving_retry_backoff", 0.05,
            "Base seconds of the serving recovery backoff; doubles per "
            "consecutive no-progress recovery (capped at 2 s), resets "
            "once any request makes progress.")
define_flag("serving_prefill_chunk", 256,
            "ServingEngine chunked-prefill granularity in tokens: a "
            "prompt longer than this prefills in fixed-size chunks "
            "interleaved with decode steps (ONE cached b=1 program per "
            "chunk length — the final partial chunk pads, so prompt "
            "length never forces a retrace), bounding the decode stall "
            "a long-prompt arrival can cause to one chunk instead of "
            "the whole prompt. Prompts at or under the chunk keep the "
            "exact monolithic prefill program. 0 = chunking off "
            "(monolithic prefill, the pre-r12 behavior). Eager-only: "
            "the chunk size reaches compiled programs through the "
            "program-cache key, never through a traced flag read.")
define_flag("serving_bucket_ladder", "4,8,16,32",
            "ServingEngine batch-bucket ladder: ','-separated decode "
            "batch sizes. The engine runs its decode step at the "
            "smallest rung covering current demand and migrates "
            "between rungs as occupancy changes (grow immediately on "
            "queue pressure, shrink after FLAGS_serving_bucket_patience "
            "idle steps); each rung's program compiles once and is "
            "cached. Rungs above the engine's max_batch are dropped and "
            "max_batch itself is always a rung, so max_batch=4 serves "
            "exactly the pre-r12 fixed-shape behavior.")
define_flag("serving_bucket_patience", 8,
            "Steps a lower bucket rung must stay sufficient before the "
            "serving engine shrinks its decode batch to it (hysteresis "
            "against occupancy flapping; growth is immediate).")
define_flag("serving_page_budget", 0,
            "USABLE KV page-pool pages for ServingEngine when "
            "num_pages is not passed, decoupling pool memory from the "
            "bucket ladder's top rung. 0 (default) keeps the "
            "worst-case formula 1 + max_batch * "
            "ceil(max_seq_len / page_size); a positive value N "
            "allocates N + 1 pages (one reserved null scribble page, "
            "like the formula's +1) and lets admission control "
            "(page-pressure queueing + prefix-cache eviction) absorb "
            "the difference.")
define_flag("serving_preempt", True,
            "SLO-aware preemption inside ServingEngine: when a "
            "tight-deadline arrival cannot admit (no free slot, or "
            "page-blocked after prefix-cache eviction), the SLACKEST "
            "running request may be unseated and re-queued for "
            "replay-from-host-state (the r10 recovery path IS the "
            "preemption mechanism, so the victim's resumed greedy "
            "continuation is bit-identical). Bounded per victim by "
            "FLAGS_serving_preempt_budget; a victim is only unseated "
            "for an arrival whose deadline slack is smaller by at "
            "least FLAGS_serving_preempt_margin seconds. Eager-only: "
            "scheduling policy, never part of a traced program.")
define_flag("serving_preempt_budget", 2,
            "How many times one request may be preempted (unseated and "
            "re-queued for replay) before it becomes untouchable — the "
            "starvation bound on SLO preemption. Preemptions never "
            "count against the replay-recovery retry budget: a "
            "preempted request is healthy, just displaced.")
define_flag("serving_preempt_horizon", 1.0,
            "Only preempt for an arrival whose deadline slack is "
            "already below this many seconds — a head with comfortable "
            "slack waits like everyone else (preemption is for "
            "endangered SLOs, not queue-jumping). Raise for slower "
            "backends; 0 disables preemption as surely as "
            "FLAGS_serving_preempt=0.")
define_flag("serving_preempt_margin", 0.0,
            "Minimum seconds of deadline-slack difference (victim "
            "slack minus arrival slack) before preemption triggers; "
            "no-deadline victims have infinite slack and always clear "
            "the margin. 0 = any tighter deadline may preempt.")
define_flag("serving_kv_host_tier_pages", 0,
            "Host-RAM KV tier capacity in pages (0 = tiering off). "
            "With a positive budget, prefix-cache eviction SPILLS cold "
            "shared pages (cache-only reference, unpinned) to host RAM "
            "instead of dropping them, and pages them back on prefix "
            "adoption — the shared-prefix working set scales past the "
            "device page budget at the cost of one host round-trip per "
            "re-adopted page. Beyond the host budget the coldest "
            "spilled pages drop entirely (classic eviction). Eager-"
            "only: pure pool bookkeeping, never traced.")
define_flag("serving_spec_gamma", 4,
            "Initial speculative-decoding draft length γ for a "
            "ServingEngine built with draft_model= — how many draft "
            "tokens one target verify checks. Snapped down to the "
            "nearest FLAGS_serving_spec_rungs rung; per-request "
            "adaptation (FLAGS_serving_spec_adaptive) takes over from "
            "there. Eager-only: γ reaches compiled programs through "
            "the program-cache key (DecodeKey.extra), never through a "
            "traced flag read.")
define_flag("serving_spec_rungs", "2,4,8",
            "','-separated γ rung set for speculative serving. Each "
            "rung compiles one draft-propose and one verify program "
            "(cached, like bucket-ladder rungs), and adaptive γ moves "
            "between rungs instead of retracing per value — steady "
            "state is zero-retrace by construction. Eager-only; part "
            "of program identity via DecodeKey.extra.")
define_flag("serving_spec_adaptive", True,
            "Per-request adaptive γ: an accept-rate EMA (the "
            "serving_spec_accept_rate signal) moves each request up a "
            "γ rung when the draft keeps agreeing and down when it "
            "keeps missing, so a hard request stops wasting draft "
            "forwards. Off = every round uses the "
            "FLAGS_serving_spec_gamma rung. Eager-only scheduling "
            "policy.")
define_flag("serving_spec_max_slots", 0,
            "Decode-slot budget speculation may bill: a speculating "
            "request prices as γ+1 decode slots (its verify covers γ+1 "
            "positions), and a step's rows only take speculation "
            "rounds when n_rows * (γ+1) fits the budget — as "
            "occupancy rises γ is capped down and finally priced out "
            "entirely (plain batched decode is the better schedule "
            "there). 0 (default) = max(max_batch, smallest rung + 1), "
            "so a lone decode row always affords the smallest rung. "
            "Eager-only.")
define_flag("serving_spec_sync_chunk", 64,
            "Chunk width (tokens) of the draft-KV catch-up sync: when "
            "a request enters speculation with its draft cache behind "
            "the target's accepted length (admission prefilled the "
            "target only, or plain decode ran while speculation was "
            "priced out), the gap teacher-forces through the draft's "
            "chunked-prefill program in fixed (1, C) chunks — one "
            "cached program, any gap length. Eager-only; the width "
            "reaches the program via the cache key.")
define_flag("serving_kv_dtype", "native",
            "KV pool storage dtype for ServingEngine pools: 'native' "
            "stores K/V at the compute dtype, 'int8' stores per-page "
            "int8 payload with per-token f32 amax scales alongside "
            "(≈2x the page budget at fixed memory). Dequantization is "
            "fused into every consuming kernel — the bf16 pool view "
            "is never materialized in HBM. Eager-only: the dtype "
            "reaches compiled programs through the program-cache key "
            "(DecodeKey.extra), never through a traced flag read.")
define_flag("fused_weight_dtype", "native",
            "Stacked-weight storage dtype for the fused N-layer "
            "decode kernel: 'native' keeps the r17 layout, 'int4' "
            "packs the merged q|k|v / gate|up / o / down matmuls two "
            "nibbles per byte with per-tile f32 scales, unpacked "
            "MXU-friendly inside the kernel's VMEM stream (2x weight "
            "memory headroom on top of int8 streaming). LayerNorm "
            "params stay native. Eager-only; part of program "
            "identity via DecodeKey.extra.")
define_flag("serving_tp_degree", 1,
            "Tensor-parallel degree of ServingEngine decode: > 1 "
            "shards the fused stacked weights column/row-wise (the "
            "shard_block_weights Megatron layout) and the paged KV "
            "pool over kv-heads across the mp axis, running the block "
            "chain under shard_map with two psums per layer. The mp "
            "process group (fleet.init) names the axis and devices "
            "when its world size matches; otherwise the first N local "
            "devices under 'mp'. Eager-only: the degree reaches "
            "compiled programs through the program-cache key "
            "(DecodeKey.extra), never a traced flag read.")
define_flag("train_max_retries", 2,
            "Model.fit step-recovery budget: retries of a failed "
            "dispatch (sync to last-good state, emergency checkpoint, "
            "backoff, re-dispatch) before the original exception "
            "propagates.")
define_flag("train_retry_backoff", 0.05,
            "Base seconds of the fit recovery backoff; doubles per "
            "attempt (capped at 2 s).")
define_flag("dataloader_max_worker_restarts", 2,
            "Per-worker restart budget for process DataLoader workers "
            "that die mid-epoch (total budget = this * num_workers); "
            "beyond it the epoch fails with the restart ledger in the "
            "message.")

# The flags a TRACED program can read (kernel dispatch, block tuning,
# matmul precision, nan checks, embedding grad mode) — the flag-tuple
# component of decode program cache keys snapshots exactly this set, so
# changing an eager-only flag (log_level, benchmark, allocator parity
# shims) never invalidates a compiled serving program.
PROGRAM_FLAGS = (
    "fused_block_decode", "fused_block_layers", "use_pallas",
    "flash_attn_min_seqlen",
    "flash_block_q", "flash_block_k", "flash_compact_stats",
    "flash_dispatch_table",
    "tpu_matmul_precision", "embedding_matmul_grad", "deterministic",
    "check_nan_inf", "check_nan_inf_level",
)


def is_tpu_backend() -> bool:
    """True when running on a real TPU — either the native "tpu" PJRT
    backend or the axon tunnel plugin. Gates Pallas-kernel dispatch."""
    import jax
    return jax.default_backend() in ("tpu", "axon")
