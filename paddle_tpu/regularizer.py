"""reference: python/paddle/regularizer.py — weight-decay regularizers
attached via ParamAttr(regularizer=...) or optimizer weight_decay. Under
the functional optimizer the coeff feeds the decoupled/L2 decay path."""


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __float__(self):
        return self.coeff

    def __repr__(self):
        return f"L2Decay(coeff={self.coeff})"


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __float__(self):
        return self.coeff

    def __repr__(self):
        return f"L1Decay(coeff={self.coeff})"
