"""Global RNG state.

Reference: paddle/phi/core/generator.cc + python/paddle/framework/random.py.
JAX randomness is functional (explicit keys); this module owns a global key
that eager random ops split from, giving paddle's stateful-RNG feel, while
jitted code paths take explicit keys (see distributed/fleet/random.py for the
TP-aware RNGStatesTracker).
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp


class _RNGState(threading.local):
    """Key creation is LAZY: materializing a PRNGKey initializes the jax
    backend, and ``import paddle_tpu`` must never touch backend state (the
    ambient TPU plugin can hang when its tunnel is down — VERDICT.md r1)."""

    def __init__(self):
        self.key = None
        self.seed_value = 0

    def get_key(self):
        if self.key is None:
            self.key = jax.random.PRNGKey(self.seed_value)
        return self.key


_state = _RNGState()


def seed(s: int):
    """``paddle.seed``: reset the global generator."""
    _state.key = jax.random.PRNGKey(int(s))
    _state.seed_value = int(s)
    return _state


def get_rng_state():
    return [_state.get_key()]


def set_rng_state(state):
    _state.key = state[0] if isinstance(state, (list, tuple)) else state


def get_cuda_rng_state():  # source compat
    return get_rng_state()


def set_cuda_rng_state(state):
    set_rng_state(state)


def next_key() -> jax.Array:
    """Split the global key and return a fresh subkey (eager random ops)."""
    _state.key, sub = jax.random.split(_state.get_key())
    return sub


def default_seed() -> int:
    return _state.seed_value
