"""Global RNG state.

Reference: paddle/phi/core/generator.cc + python/paddle/framework/random.py.
JAX randomness is functional (explicit keys); this module owns a global key
that eager random ops split from, giving paddle's stateful-RNG feel, while
jitted code paths take explicit keys (see distributed/fleet/random.py for the
TP-aware RNGStatesTracker).
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp


class _RNGState(threading.local):
    def __init__(self):
        self.key = jax.random.PRNGKey(0)
        self.seed_value = 0


_state = _RNGState()


def seed(s: int):
    """``paddle.seed``: reset the global generator."""
    _state.key = jax.random.PRNGKey(int(s))
    _state.seed_value = int(s)
    return _state


def get_rng_state():
    return [_state.key]


def set_rng_state(state):
    _state.key = state[0] if isinstance(state, (list, tuple)) else state


def get_cuda_rng_state():  # source compat
    return get_rng_state()


def set_cuda_rng_state(state):
    set_rng_state(state)


def next_key() -> jax.Array:
    """Split the global key and return a fresh subkey (eager random ops)."""
    _state.key, sub = jax.random.split(_state.key)
    return sub


def default_seed() -> int:
    return _state.seed_value
