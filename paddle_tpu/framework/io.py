"""Checkpoint save/load.

Reference: python/paddle/framework/io.py (``paddle.save``/``paddle.load`` —
pickled state dicts, .pdparams/.pdopt convention). Tensors round-trip
through numpy; nested dicts/lists are preserved. Sharded / resharding
checkpoints live in paddle_tpu.distributed.checkpoint (orbax-backed).
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor


class _TensorPayload:
    """Pickle-stable wrapper (numpy + metadata)."""

    def __init__(self, t: Tensor):
        v = np.asarray(t._value)
        # numpy can't represent bfloat16: store as uint16 view + marker
        if str(t._value.dtype) == "bfloat16":
            self.dtype = "bfloat16"
            self.array = np.asarray(t._value.astype(jnp.float32))
        else:
            self.dtype = str(v.dtype)
            self.array = v
        self.stop_gradient = t.stop_gradient
        self.name = t.name
        self.is_parameter = isinstance(t, Parameter)

    def to_tensor(self) -> Tensor:
        arr = jnp.asarray(self.array)
        if self.dtype == "bfloat16":
            arr = arr.astype(jnp.bfloat16)
        if self.is_parameter:
            t = Parameter(arr, name=self.name)
            t.stop_gradient = self.stop_gradient
            return t
        return Tensor(arr, stop_gradient=self.stop_gradient, name=self.name)


def _pack(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        return _TensorPayload(obj)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj: Any, return_numpy=False) -> Any:
    if isinstance(obj, _TensorPayload):
        return obj.array if return_numpy else obj.to_tensor()
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs) -> None:
    """``paddle.save``: pickle nested structures of Tensors to ``path``.

    ``checkpoint_save`` is a fault-injection site (FLAGS_fault_inject):
    it fires BEFORE anything touches disk, so an injected save failure
    never leaves a truncated checkpoint behind."""
    from ..testing import faults
    faults.check("checkpoint_save", path=path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    """``paddle.load``: inverse of save."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
