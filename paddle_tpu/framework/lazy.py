"""Lazy (meta) parameter initialization.

Reference: paddle.LazyGuard (python/paddle/nn/initializer/lazy_init.py) —
construct arbitrarily large models without allocating parameter memory.
Inside the guard, ``Layer.create_parameter`` skips the initializer and
stores a ``jax.ShapeDtypeStruct`` as the Parameter value (a meta tensor:
shape + dtype, zero bytes). Consumers that only need structure — abstract
program lowering (``PipelineTrainStep(abstract=True)``), sharding planners,
``jit.save`` input specs — work unchanged; running compute on a lazy model
raises naturally until the values are materialized (e.g. by a checkpoint
load or ``Layer.load_raw_state``).
"""

from __future__ import annotations

_LAZY = False


class LazyGuard:
    """Context manager: parameters created inside are meta tensors."""

    def __enter__(self):
        global _LAZY
        self._prev = _LAZY
        _LAZY = True
        return self

    def __exit__(self, *exc):
        global _LAZY
        _LAZY = self._prev
        return False


def in_lazy_init() -> bool:
    return _LAZY
