"""Lazy (meta) parameter initialization.

Reference: paddle.LazyGuard (python/paddle/nn/initializer/lazy_init.py) —
construct arbitrarily large models without allocating parameter memory.
Inside the guard, ``Layer.create_parameter`` skips the initializer and
stores a ``jax.ShapeDtypeStruct`` as the Parameter value (a meta tensor:
shape + dtype, zero bytes). Consumers that only need structure — abstract
program lowering (``PipelineTrainStep(abstract=True)``), sharding planners,
``jit.save`` input specs — work unchanged; running compute on a lazy model
raises naturally until the values are materialized (e.g. by a checkpoint
load or ``Layer.load_raw_state``).
"""

from __future__ import annotations

_LAZY = False
_EPOCH = 0


class LazyGuard:
    """Context manager: parameters created inside are meta tensors.

    Each outermost guard opens a new *epoch*: parameters created under
    separate ``with LazyGuard():`` blocks live in separate registries, so
    materializing one model never touches another (models built inside
    the SAME guard share an epoch and replay their interleaved RNG stream
    together, exactly as an eager build would)."""

    def __enter__(self):
        global _LAZY, _EPOCH
        self._prev = _LAZY
        if not _LAZY:
            _EPOCH += 1
        _LAZY = True
        return self

    def __exit__(self, *exc):
        global _LAZY
        _LAZY = self._prev
        return False


def in_lazy_init() -> bool:
    return _LAZY


def is_lazy(tensor) -> bool:
    """True when ``tensor`` is a meta tensor created under ``LazyGuard``
    (its value is a ``jax.ShapeDtypeStruct`` — shape+dtype, no bytes)."""
    import jax
    return tensor is not None and isinstance(
        getattr(tensor, "_value", None), jax.ShapeDtypeStruct)


# Per-epoch creation-order registries of lazy parameters. Initializers
# draw from the GLOBAL framework RNG stream (framework.random.next_key),
# so replaying them out of creation order would permute the stream and
# produce different weights than an eager build with the same seed.
# Registry: {"entries": [[init, weakref] | None], "swept": int,
# "live": int, "rng_state": key}; a parameter's ``_lazy_init`` holds
# (epoch, index). The materialization dtype is the param struct's
# CURRENT dtype (Layer.to retypes meta params), not a recorded one.
# materialize_parameter(p) sweeps every live entry of p's OWN epoch
# created before p first, which makes the lazy path bit-identical to
# eager construction (tested: TestLazyStreamingQuantize). Entries retire
# (-> None) on successful init or when the parameter is garbage-collected
# (weakref callback), and an epoch whose live count hits zero is dropped
# wholesale — initializer objects don't outlive their model.
_REGISTRIES: dict = {}
_CONSUMED = object()  # sentinel: weight was eaten by streaming quantization


def _retire(reg: dict, epoch: int, idx: int) -> None:
    if reg["entries"][idx] is not None:
        reg["entries"][idx] = None
        reg["live"] -= 1
        if reg["live"] == 0:
            _REGISTRIES.pop(epoch, None)


def register_lazy(p, init) -> None:
    import weakref
    reg = _REGISTRIES.get(_EPOCH)
    if reg is None:
        # snapshot the global RNG stream position: materialization
        # replays inits from HERE, so draws between construction and
        # materialize() cannot shift the replayed weights
        from .random import get_rng_state
        reg = _REGISTRIES[_EPOCH] = {"entries": [], "swept": 0, "live": 0,
                                     "rng_state": get_rng_state()}
    idx = len(reg["entries"])
    p._lazy_init = (_EPOCH, idx)
    epoch = _EPOCH

    def _gone(_ref, _e=epoch, _i=idx):
        r = _REGISTRIES.get(_e)
        if r is not None:
            _retire(r, _e, _i)

    reg["entries"].append([init, weakref.ref(p, _gone)])
    reg["live"] += 1


def mark_consumed(p) -> None:
    """Streaming quantization re-lazifies a source weight after folding it
    into an int8 buffer; mark it so later materialization attempts fail
    loudly instead of silently skipping or crashing mid-op."""
    p._lazy_init = _CONSUMED


def materialize_parameter(p) -> None:
    """Run a lazy parameter's recorded initializer in-place (no-op when
    already live), after first materializing every lazy parameter created
    before it in the same epoch (RNG-stream order — see ``_REGISTRIES``).
    Raises when the parameter predates initializer recording: load values
    instead.

    RNG semantics: the sweep restores the stream position snapshotted at
    the epoch's first lazy creation, so the replayed weights are
    bit-identical to an eager build with the same seed even when other
    RNG consumers ran between construction and materialization (those
    consumers themselves see a different stream than an eager interleave
    would give them — the weights are the guarantee). An initializer that
    raises (e.g. OOM) leaves its entry pending at the exact stream
    position it started from, so a retry replays it identically.

    Caveat: a lazy parameter garbage-collected (or checkpoint-loaded)
    before materialization is skipped without consuming its RNG keys, so
    later parameters shift relative to an eager build that DID initialize
    it."""
    if not is_lazy(p):
        return
    rec = getattr(p, "_lazy_init", None)
    if rec is _CONSUMED:
        raise RuntimeError(
            f"lazy parameter {p.name!r} was consumed by streaming "
            "quantization (nn.quant.QuantizedLinear.from_linear); the "
            "quantized layer replaced it — this source layer is dead")
    if rec is None:
        raise RuntimeError(
            f"lazy parameter {p.name!r} has no recorded initializer; "
            "materialize it by loading a checkpoint (set_state_dict / "
            "load_raw_state)")
    epoch, idx = rec
    reg = _REGISTRIES.get(epoch)
    if reg is None:  # every entry retired yet p still lazy: stale _lazy_init
        raise RuntimeError(
            f"lazy parameter {p.name!r}'s registry epoch was already "
            "retired; materialize it by loading a checkpoint")
    from .random import get_rng_state, set_rng_state
    outer = get_rng_state()
    set_rng_state(reg["rng_state"])
    try:
        for i in range(reg["swept"], idx + 1):
            entry = reg["entries"][i]
            if entry is None:
                continue
            init, ref = entry
            q = ref()
            if q is not None and is_lazy(q) and getattr(
                    q, "_lazy_init", None) == (epoch, i):
                # honor the struct's CURRENT dtype, not the recorded one:
                # Layer.to(dtype=...) retypes meta params before
                # materialization (the 7B-int8 flow builds bf16 this way)
                q._value = init(tuple(q._value.shape), q._value.dtype)
            _retire(reg, epoch, i)  # retire only after a successful init
    finally:
        # resume point for later sweeps (exact even after a failed init),
        # then hand the ambient stream back untouched
        reg["rng_state"] = get_rng_state()
        set_rng_state(outer)
    n = len(reg["entries"])
    while reg["swept"] < n and reg["entries"][reg["swept"]] is None:
        reg["swept"] += 1


def materialize(layer) -> "object":
    """Materialize every remaining lazy parameter of ``layer`` in-place
    by running its recorded initializer (reference: paddle.LazyGuard's
    deferred startup program). Use after :func:`LazyGuard`-scoped
    construction when no checkpoint will be loaded — e.g. randomly
    initialized benchmarks, or after ``nn.quant.quantize_linears`` has
    streamed the Linear weights into int8 and only embeddings/norms
    remain lazy. Returns ``layer``."""
    for _, p in layer.named_parameters():
        materialize_parameter(p)
    return layer
