"""paddle.audio.datasets — TESS / ESC-50.

Reference: python/paddle/audio/datasets/{tess.py,esc50.py}. Zero network
egress: ``download=True`` (the reference default) raises with guidance;
local archives laid out in the reference's extracted structure load
through the stdlib wave backend.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..io.dataset import Dataset
from . import backends, features

__all__ = ["TESS", "ESC50"]


class _WavFolderDataset(Dataset):
    NAME = "dataset"

    def __init__(self, data_dir: Optional[str], mode: str,
                 feat_type: str = "raw", archive=None, download: bool = False,
                 **feat_kwargs):
        if download or data_dir is None:
            raise ValueError(
                f"{type(self).__name__}: download is unsupported (no "
                f"network egress); extract the {self.NAME} archive locally "
                f"and pass data_dir=<extracted folder>")
        if not os.path.isdir(data_dir):
            raise FileNotFoundError(data_dir)
        self.mode = mode
        self.feat_type = feat_type
        self._feat = self._make_feat(feat_type, feat_kwargs)
        self.files, self.labels = self._index(data_dir)

    def _make_feat(self, feat_type: str, kw) -> Optional[Callable]:
        if feat_type == "raw":
            return None
        cls = {"spectrogram": features.Spectrogram,
               "melspectrogram": features.MelSpectrogram,
               "logmelspectrogram": features.LogMelSpectrogram,
               "mfcc": features.MFCC}.get(feat_type)
        if cls is None:
            raise ValueError(f"unknown feat_type {feat_type!r}")
        return cls(**kw)

    def _index(self, data_dir: str) -> Tuple[List[str], List[int]]:
        raise NotImplementedError

    def __getitem__(self, idx):
        wav, _sr = backends.load(self.files[idx])
        x = wav.numpy()[0]           # mono channel 0
        if self._feat is not None:
            from ..core.tensor import Tensor
            x = self._feat(Tensor(x[None, :])).numpy()[0]
        return x, np.int64(self.labels[idx])

    def __len__(self):
        return len(self.files)


class TESS(_WavFolderDataset):
    """Toronto Emotional Speech Set: <data_dir>/<speaker>_<word>_<emotion>
    folders of wav files; label = emotion index (reference label set)."""

    NAME = "TESS"
    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, mode: str = "train", n_folds: int = 5,
                 split: int = 1, feat_type: str = "raw",
                 data_dir: Optional[str] = None, download: bool = False,
                 **kw):
        self.n_folds = n_folds
        self.split = split
        super().__init__(data_dir, mode, feat_type, download=download, **kw)

    def _index(self, data_dir):
        files, labels = [], []
        for root, _dirs, names in sorted(os.walk(data_dir)):
            for n in sorted(names):
                if not n.lower().endswith(".wav"):
                    continue
                emo = n.rsplit("_", 1)[-1][:-4].lower()
                if emo in self.EMOTIONS:
                    files.append(os.path.join(root, n))
                    labels.append(self.EMOTIONS.index(emo))
        fold = np.arange(len(files)) % self.n_folds + 1
        keep = (fold != self.split) if self.mode == "train" \
            else (fold == self.split)
        return ([f for f, k in zip(files, keep) if k],
                [l for l, k in zip(labels, keep) if k])


class ESC50(_WavFolderDataset):
    """ESC-50 environmental sounds: wav names ``{fold}-{id}-{take}-
    {target}.wav`` under <data_dir>/audio (reference layout)."""

    NAME = "ESC50"

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw", data_dir: Optional[str] = None,
                 download: bool = False, **kw):
        self.split = split
        super().__init__(data_dir, mode, feat_type, download=download, **kw)

    def _index(self, data_dir):
        audio = os.path.join(data_dir, "audio")
        if not os.path.isdir(audio):
            audio = data_dir
        files, labels = [], []
        for n in sorted(os.listdir(audio)):
            if not n.endswith(".wav"):
                continue
            parts = n[:-4].split("-")
            if len(parts) != 4:
                continue
            fold, target = int(parts[0]), int(parts[3])
            if (self.mode == "train") == (fold != self.split):
                files.append(os.path.join(audio, n))
                labels.append(target)
        return files, labels
