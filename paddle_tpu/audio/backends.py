"""paddle.audio.backends — audio file IO.

Reference: python/paddle/audio/backends/ (wave_backend default, optional
soundfile). This environment has no soundfile; the stdlib ``wave``
backend implements the same trio (``info``/``load``/``save``) for PCM
WAV — the reference's wave_backend scope — and the backend-selection
API reports exactly what is available instead of pretending.
"""

from __future__ import annotations

import wave
from dataclasses import dataclass
from typing import List, Tuple, Union

import numpy as np

from ..core.tensor import Tensor

__all__ = ["AudioInfo", "info", "load", "save",
           "list_available_backends", "get_current_backend", "set_backend"]


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


def list_available_backends() -> List[str]:
    return ["wave_backend"]


def get_current_backend() -> str:
    return "wave_backend"


def set_backend(backend_name: str) -> None:
    if backend_name != "wave_backend":
        raise NotImplementedError(
            f"backend {backend_name!r} is unavailable (soundfile is not "
            f"installed in this environment); only 'wave_backend' exists")


def info(filepath: str) -> AudioInfo:
    with wave.open(filepath, "rb") as f:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=8 * f.getsampwidth())


_WIDTH_DTYPE = {1: np.uint8, 2: np.int16, 4: np.int32}


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True,
         channels_first: bool = True) -> Tuple[Tensor, int]:
    """(waveform, sample_rate); waveform float32 in [-1, 1] when
    ``normalize`` (reference semantics), shape (C, T) when
    ``channels_first``."""
    with wave.open(filepath, "rb") as f:
        sr, nch, width = f.getframerate(), f.getnchannels(), f.getsampwidth()
        f.setpos(min(frame_offset, f.getnframes()))
        n = f.getnframes() - f.tell() if num_frames < 0 else num_frames
        raw = f.readframes(n)
    if width not in _WIDTH_DTYPE:
        raise ValueError(f"unsupported PCM sample width {width}")
    data = np.frombuffer(raw, dtype=_WIDTH_DTYPE[width]).reshape(-1, nch)
    if normalize:
        if width == 1:    # unsigned 8-bit: center, then scale
            wavef = (data.astype(np.float32) - 128.0) / 128.0
        else:
            wavef = data.astype(np.float32) / float(2 ** (8 * width - 1))
    else:
        # reference wave_backend: raw PCM values in the file's own dtype
        # (uint8 stays [0, 255] uncentered, int16/int32 stay integer)
        wavef = data.copy()
    if channels_first:
        wavef = wavef.T
    return Tensor(wavef, stop_gradient=True), sr


def save(filepath: str, src: Union[Tensor, np.ndarray], sample_rate: int,
         channels_first: bool = True, encoding: str = "PCM_16",
         bits_per_sample: int = 16) -> None:
    if encoding != "PCM_16" or bits_per_sample != 16:
        raise NotImplementedError(
            "wave_backend writes PCM_16 only (reference wave_backend has "
            "the same restriction)")
    x = np.asarray(src._value if isinstance(src, Tensor) else src)
    if x.ndim == 1:
        x = x[None, :] if channels_first else x[:, None]
    if channels_first:
        x = x.T                       # -> (T, C)
    x = np.clip(x, -1.0, 1.0)
    pcm = (x * 32767.0).astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(pcm.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())
