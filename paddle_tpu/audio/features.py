"""paddle.audio.features — Spectrogram / MelSpectrogram /
LogMelSpectrogram / MFCC layers.

Reference: python/paddle/audio/features/layers.py. Built on
``paddle_tpu.signal.stft`` (jit-friendly framing + rfft) with the
filterbank/DCT constants from :mod:`.functional` folded in at layer
construction — the whole feature pipeline traces into one XLA program.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from .. import signal as _signal
from ..core.tensor import Tensor
from ..nn.layer import Layer
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    """|STFT|^power of shape (..., n_fft//2 + 1, num_frames)."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = F.get_window(window, self.win_length, dtype=dtype)

    def forward(self, x: Tensor) -> Tensor:
        spec = _signal.stft(
            x, n_fft=self.n_fft, hop_length=self.hop_length,
            win_length=self.win_length, window=self.fft_window,
            center=self.center, pad_mode=self.pad_mode, onesided=True)
        v = spec._value if isinstance(spec, Tensor) else spec
        mag = jnp.abs(v)
        if self.power != 1.0:
            mag = mag ** self.power
        return Tensor(mag, stop_gradient=x.stop_gradient)


class MelSpectrogram(Layer):
    """Spectrogram → mel filterbank: (..., n_mels, num_frames)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(
            n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode,
            dtype=dtype)
        self.fbank_matrix = F.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)
        self.n_mels = n_mels

    def forward(self, x: Tensor) -> Tensor:
        spec = self._spectrogram(x)
        mel = jnp.matmul(self.fbank_matrix._value, spec._value)
        return Tensor(mel, stop_gradient=x.stop_gradient)


class LogMelSpectrogram(Layer):
    """MelSpectrogram → power_to_db."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length,
            win_length=win_length, window=window, power=power,
            center=center, pad_mode=pad_mode, n_mels=n_mels, f_min=f_min,
            f_max=f_max, htk=htk, norm=norm, dtype=dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x: Tensor) -> Tensor:
        mel = self._melspectrogram(x)
        return F.power_to_db(mel, ref_value=self.ref_value, amin=self.amin,
                             top_db=self.top_db)


class MFCC(Layer):
    """LogMelSpectrogram → DCT-II: (..., n_mfcc, num_frames)."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        if n_mfcc > n_mels:
            raise ValueError(f"n_mfcc ({n_mfcc}) cannot exceed n_mels "
                             f"({n_mels})")
        self._log_melspectrogram = LogMelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length,
            win_length=win_length, window=window, power=power,
            center=center, pad_mode=pad_mode, n_mels=n_mels, f_min=f_min,
            f_max=f_max, htk=htk, norm=norm, ref_value=ref_value,
            amin=amin, top_db=top_db, dtype=dtype)
        self.dct_matrix = F.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x: Tensor) -> Tensor:
        logmel = self._log_melspectrogram(x)
        v = logmel._value
        out = jnp.einsum("...mt,mk->...kt", v, self.dct_matrix._value)
        return Tensor(out, stop_gradient=x.stop_gradient)
