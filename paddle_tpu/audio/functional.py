"""paddle.audio.functional — mel/dB/DCT helpers.

Reference: python/paddle/audio/functional/functional.py (hz_to_mel,
mel_to_hz, mel_frequencies, fft_frequencies, compute_fbank_matrix,
power_to_db, create_dct) and window.py (get_window). Same math (HTK and
Slaney mel scales, Slaney-normalized filterbanks, orthonormal DCT-II),
computed with numpy at feature-build time — filterbanks are constants
folded into the jitted feature pipeline, not traced ops.
"""

from __future__ import annotations

import math
from typing import Optional, Union

import numpy as np

from ..core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def _asarray(x):
    if isinstance(x, Tensor):
        return np.asarray(x._value)
    return np.asarray(x)


def _maybe_tensor(x, like):
    if isinstance(like, Tensor) or not np.isscalar(like):
        return Tensor(np.asarray(x, np.float32), stop_gradient=True)
    return float(x)


def hz_to_mel(freq: Union[float, Tensor], htk: bool = False):
    """Hz → mel. ``htk=True``: 2595·log10(1 + f/700); else the Slaney
    piecewise-linear/log scale (reference default)."""
    f = _asarray(freq).astype(np.float64)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep,
                       mel)
    return _maybe_tensor(mel, freq)


def mel_to_hz(mel: Union[float, Tensor], htk: bool = False):
    m = _asarray(mel).astype(np.float64)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = np.where(m >= min_log_mel,
                      min_log_hz * np.exp(logstep * (m - min_log_mel)),
                      hz)
    return _maybe_tensor(hz, mel)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False):
    """``n_mels`` frequencies evenly spaced on the mel scale."""
    lo = _asarray(hz_to_mel(f_min, htk=htk))
    hi = _asarray(hz_to_mel(f_max, htk=htk))
    mels = np.linspace(float(lo), float(hi), n_mels)
    return Tensor(_asarray(mel_to_hz(mels, htk=htk)).astype(np.float32),
                  stop_gradient=True)


def fft_frequencies(sr: int, n_fft: int):
    return Tensor(np.linspace(0, sr / 2, 1 + n_fft // 2).astype(np.float32),
                  stop_gradient=True)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: Union[str, float] = "slaney",
                         dtype: str = "float32"):
    """(n_mels, 1 + n_fft//2) triangular mel filterbank (librosa-compatible,
    as the reference's)."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = np.asarray(fft_frequencies(sr, n_fft)._value, np.float64)
    mel_f = np.asarray(
        mel_frequencies(n_mels + 2, f_min, f_max, htk)._value, np.float64)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    elif norm is not None:
        weights = weights / np.maximum(
            np.linalg.norm(weights, ord=float(norm), axis=1, keepdims=True),
            1e-10)
    return Tensor(weights.astype(dtype), stop_gradient=True)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """10·log10(S/ref) with amin floor and optional top_db clamp."""
    import jax.numpy as jnp
    x = spect._value if isinstance(spect, Tensor) else jnp.asarray(spect)
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec, stop_gradient=isinstance(spect, Tensor)
                  and spect.stop_gradient)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype: str = "float32"):
    """(n_mels, n_mfcc) DCT-II basis (orthonormal under norm='ortho')."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)
    basis = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(n_mels)
        basis[:, 1:] *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return Tensor(basis.astype(dtype), stop_gradient=True)


def get_window(window: Union[str, tuple], win_length: int,
               fftbins: bool = True, dtype: str = "float32"):
    """Window vector by name ('hann', 'hamming', 'blackman', 'bartlett',
    'kaiser' (with beta), 'gaussian' (with std), 'taylor' unsupported)."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    M = win_length + (0 if fftbins else -1)
    n = np.arange(win_length, dtype=np.float64)
    denom = max(M, 1)
    if name == "hann":
        w = 0.5 - 0.5 * np.cos(2 * math.pi * n / denom)
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * math.pi * n / denom)
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * math.pi * n / denom)
             + 0.08 * np.cos(4 * math.pi * n / denom))
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * n / denom - 1.0)
    elif name == "kaiser":
        beta = args[0] if args else 12.0
        w = np.i0(beta * np.sqrt(np.maximum(
            0.0, 1 - (2 * n / denom - 1) ** 2))) / np.i0(beta)
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = np.exp(-0.5 * ((n - M / 2) / std) ** 2)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(w.astype(dtype), stop_gradient=True)
