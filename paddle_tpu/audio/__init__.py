"""paddle.audio — audio features, IO backends, datasets.

Reference: python/paddle/audio/__init__.py (exposes ``functional``,
``features``, ``backends``, ``datasets``)."""

from . import backends, datasets, features, functional

__all__ = ["backends", "datasets", "features", "functional"]
