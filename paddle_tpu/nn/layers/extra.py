"""Layer wrappers for the extended functional surface
(reference: python/paddle/nn/layer/{conv,pooling,norm,loss,distance}.py)."""

from __future__ import annotations

import math

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer import Layer
from ..param_attr import ParamAttr


class _ConvNd(Layer):
    _NDIM = 2

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None,
                 transpose=False, output_padding=0):
        super().__init__()
        nd = self._NDIM
        ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._in, self._out = in_channels, out_channels
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        self._output_padding = output_padding
        if transpose:
            wshape = (in_channels, out_channels // groups) + ks
        else:
            wshape = (out_channels, in_channels // groups) + ks
        fan_in = in_channels // groups * int(math.prod(ks))
        std = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            wshape, attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else I.Uniform(-std, std))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_channels,), attr=ParamAttr._to_attr(bias_attr),
                is_bias=True,
                default_initializer=None if bias_attr else
                I.Uniform(-std, std))

    def extra_repr(self):
        return f"{self._in}, {self._out}, stride={self._stride}"


class Conv1D(_ConvNd):
    _NDIM = 1

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups)


class Conv3D(_ConvNd):
    _NDIM = 3

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups)


class Conv1DTranspose(_ConvNd):
    _NDIM = 1

    def __init__(self, *args, **kwargs):
        super().__init__(*args, transpose=True, **kwargs)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation)


class Conv3DTranspose(_ConvNd):
    _NDIM = 3

    def __init__(self, *args, **kwargs):
        super().__init__(*args, transpose=True, **kwargs)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation)


class _Pool(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding
        # ceil_mode / exclusive / data_format ride through to the functional
        kw.pop("name", None)
        self._kw = kw

    def extra_repr(self):
        return f"kernel_size={self._k}, stride={self._s}, padding={self._p}"


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self._k, self._s, self._p, **self._kw)


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self._k, self._s, self._p, **self._kw)


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self._k, self._s, self._p, **self._kw)


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self._k, self._s, self._p, **self._kw)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._o = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._o)


class AdaptiveAvgPool3D(AdaptiveAvgPool1D):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._o)


class AdaptiveMaxPool1D(AdaptiveAvgPool1D):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._o)


class AdaptiveMaxPool2D(AdaptiveAvgPool1D):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._o)


class AdaptiveMaxPool3D(AdaptiveAvgPool1D):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._o)


class _InstanceNorm(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._eps = epsilon
        if weight_attr is False:
            self.scale = None
        else:
            self.scale = self.create_parameter(
                (num_features,), attr=ParamAttr._to_attr(weight_attr),
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._eps)


class InstanceNorm1D(_InstanceNorm):
    pass


class InstanceNorm2D(_InstanceNorm):
    pass


class InstanceNorm3D(_InstanceNorm):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self._args)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._groups, self._fmt = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self._groups, self._fmt)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self._padding, self._fmt = padding, data_format

    def forward(self, x):
        return F.zeropad2d(x, self._padding, self._fmt)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings,
                      dilations)

    def forward(self, x):
        return F.fold(x, *self._args)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self._args)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._args = (p, epsilon, keepdim)

    def forward(self, x, y):
        return F.pairwise_distance(x, y, *self._args)


class Bilinear(Layer):
    """(reference: python/paddle/nn/layer/common.py::Bilinear)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(in1_features)
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else I.Uniform(-std, std))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


# ------------------------------------------------------------ loss layers
class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self._blank, self._reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self._blank, self._reduction, norm_by_times)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self._margin,
                                     self._reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self._margin,
                                      self._reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self._reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight, self._reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self._weight,
                                              self._reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self._margin,
                                       self._reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self._args)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self._args)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self._args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, *self._args)


# ------------------------------------------- coverage-manifest layer batch
class AlphaDropout(Layer):
    """reference: nn/layer/common.py AlphaDropout (SELU-preserving)."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._args = (delta, reduction)

    def forward(self, input, label):
        return F.huber_loss(input, label, *self._args)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._p, self._margin, self._weight = p, margin, weight
        self._reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, p=self._p,
                                   margin=self._margin, weight=self._weight,
                                   reduction=self._reduction)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, training=self.training)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self._threshold, self._value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold, self._value)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._r, self._fmt = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self._r, self._fmt)


class _PadND(Layer):
    _fmt = "NCHW"

    def __init__(self, padding, mode="constant", value=0.0,
                 data_format=None, name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format or self._fmt

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadND):
    _fmt = "NCL"


class Pad3D(_PadND):
    _fmt = "NCDHW"


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format)
        self._output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self._args[0], self._args[1],
                              self._args[2], self._args[3],
                              self._output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format)
        self._output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self._args[0], self._args[1],
                              self._args[2], self._args[3],
                              self._output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format)
        self._output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self._args[0], self._args[1],
                              self._args[2], self._args[3],
                              self._output_size)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._kw = dict(size=size, scale_factor=scale_factor,
                        mode="nearest", data_format=data_format)

    def forward(self, x):
        return F.interpolate(x, **self._kw)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._kw = dict(size=size, scale_factor=scale_factor,
                        mode="bilinear", align_corners=True,
                        data_format=data_format)

    def forward(self, x):
        return F.interpolate(x, **self._kw)


class SpectralNorm(Layer):
    """reference: nn/layer/norm.py SpectralNorm — normalizes an input
    WEIGHT tensor by its largest singular value via power iteration.
    The u/v estimates are buffers updated eagerly per forward (inside a
    jitted program the update is functional: same math, no persistence —
    the reference trains eagerly here too)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        import numpy as _np
        self._dim, self._iters, self._eps = dim, power_iters, epsilon
        h = weight_shape[dim]
        w = int(_np.prod(weight_shape)) // h
        from ...core.tensor import Tensor as _T
        rng = _np.random.default_rng(0)
        self.register_buffer("weight_u", _T(
            rng.standard_normal(h).astype("float32"), stop_gradient=True))
        self.register_buffer("weight_v", _T(
            rng.standard_normal(w).astype("float32"), stop_gradient=True))

    def forward(self, weight):
        import jax as _jax
        import jax.numpy as jnp
        from ... import ops as _ops
        from ...core.tensor import Tensor as _T, _val as _v
        w = _v(weight)
        perm = [self._dim] + [i for i in range(w.ndim) if i != self._dim]
        wm = jnp.transpose(w, perm).reshape(w.shape[self._dim], -1)
        u, v = _v(self.weight_u), _v(self.weight_v)
        for _ in range(self._iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + self._eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + self._eps)
        if not isinstance(u, _jax.core.Tracer):
            self.weight_u._value = u
            self.weight_v._value = v
        # sigma via TAPE-RECORDED ops on the input weight so grads flow
        w_mat = _ops.reshape(_ops.transpose(weight, perm),
                             [w.shape[self._dim], -1])
        u_t = _T(u, stop_gradient=True)
        v_t = _T(v, stop_gradient=True)
        sigma = _ops.matmul(_ops.matmul(_ops.unsqueeze(u_t, 0), w_mat),
                            _ops.unsqueeze(v_t, -1)).reshape([])
        return weight / sigma


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        super().__init__()
        self._blank, self._reduction = blank, reduction

    def forward(self, logits, labels, logit_lengths, label_lengths):
        return F.rnnt_loss(logits, labels, logit_lengths, label_lengths,
                           blank=self._blank, reduction=self._reduction)


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self._padding = padding if isinstance(padding, (list, tuple)) \
            else (padding, padding)
        self._fmt = data_format

    def forward(self, x):
        pad = list(self._padding)
        axis = -1 if self._fmt == "NCL" else 1
        return F.pad(x, [0, 0] * (2 if self._fmt == "NCL" else 1)
                     + pad if axis == -1 else pad, mode="constant")


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        p = padding
        self._padding = (p,) * 6 if isinstance(p, int) else tuple(p)
        self._fmt = data_format

    def forward(self, x):
        return F.pad(x, list(self._padding), mode="constant",
                     data_format=self._fmt)


class Unflatten(Layer):
    """reference: paddle.nn.Unflatten."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self._axis, self._shape = axis, tuple(shape)

    def forward(self, x):
        from ...ops.manipulation import unflatten as _unf
        return _unf(x, self._axis, self._shape)


class Softmax2D(Layer):
    """reference: paddle.nn.Softmax2D — softmax over the channel dim of
    (N, C, H, W)."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class Silu(Layer):
    def forward(self, x):
        return F.silu(x)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self._p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self._p, training=self.training)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        d, m, s, r = self._args
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, distance_function=d, margin=m,
            swap=s, reduction=r)


class HSigmoidLoss(Layer):
    """reference: paddle.nn.HSigmoidLoss — holds the tree weights."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.XavierNormal())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (num_classes - 1, 1), is_bias=True)
        else:
            self.bias = None

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self.num_classes,
                               self.weight, self.bias)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """reference: paddle.nn.AdaptiveLogSoftmaxWithLoss."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        self.cutoffs = list(cutoffs)
        self.n_clusters = len(self.cutoffs)
        shortlist = self.cutoffs[0]
        self.head_weight = self.create_parameter(
            (shortlist + self.n_clusters, in_features),
            default_initializer=I.XavierNormal())
        self.head_bias = (self.create_parameter(
            (shortlist + self.n_clusters,), is_bias=True)
            if head_bias else None)
        self.tail_weights = []
        bounds = self.cutoffs + [n_classes]
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = bounds[i + 1] - bounds[i]
            proj = self.create_parameter((hsz, in_features),
                                         default_initializer=I.XavierNormal())
            w = self.create_parameter((osz, hsz),
                                      default_initializer=I.XavierNormal())
            setattr(self, f"tail_proj_{i}", proj)
            setattr(self, f"tail_w_{i}", w)
            self.tail_weights += [proj, w]

    def forward(self, input, label):
        out, loss = F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs, head_bias=self.head_bias)
        return out, loss


class FractionalMaxPool2D(Layer):
    """reference: paddle.nn.FractionalMaxPool2D — pseudo-random
    fractional pooling (Graham 2014); the region sequence is derived
    from output_size with the deterministic 'pseudo' scheme."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._out = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return _fractional_pool(x, self._out, nd=2,
                                return_mask=self._return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._out = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return _fractional_pool(x, self._out, nd=3,
                                return_mask=self._return_mask)


def _fractional_pool(x, output_size, nd, return_mask=False):
    from ...core.tensor import apply_op as _ap
    import jax.numpy as _jnp
    v = x._value if hasattr(x, "_value") else x
    spatial = v.shape[-nd:]
    outs = ((output_size,) * nd if isinstance(output_size, int)
            else tuple(output_size))

    def fn(a):
        out = a
        for d in range(nd):
            axis = a.ndim - nd + d
            n_in, n_out = spatial[d], outs[d]
            # deterministic fractional boundaries: floor(i * n_in/n_out)
            edges = _jnp.floor(
                _jnp.arange(n_out + 1) * (n_in / n_out)).astype(int)
            pieces = [
                _jnp.max(_jnp.take(out, _jnp.arange(int(edges[i]),
                                                    max(int(edges[i]) + 1,
                                                        int(edges[i + 1]))),
                                   axis=axis), axis=axis, keepdims=True)
                for i in range(n_out)]
            out = _jnp.concatenate(pieces, axis=axis)
        return out
    res = _ap("fractional_max_pool", fn, x)
    if return_mask:
        raise NotImplementedError("fractional pool return_mask")
    return res
