"""Core layers (reference: python/paddle/nn/layer/{common,norm,activation}.py)."""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp

from ...core.tensor import Parameter, Tensor
from .. import functional as F
from .. import initializer as I
from ..layer import Layer
from ..param_attr import ParamAttr


class Linear(Layer):
    """y = xW + b, weight shape [in_features, out_features]
    (reference: python/paddle/nn/layer/common.py::Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self._in_features, self._out_features = in_features, out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else I.XavierNormal())
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Embedding(Layer):
    """Lookup table, weight shape [num_embeddings, embedding_dim]."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else I.Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._value = self.weight._value.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=ParamAttr._to_attr(weight_attr),
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """Root-mean-square norm (reference fused op:
    paddle/phi/kernels/gpu/rms_norm_kernel.cu; here one XLA fusion)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class BatchNorm1D(Layer):
    _dims = 1

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__()
        self._momentum, self._epsilon = momentum, epsilon
        self._use_global_stats = use_global_stats
        self._data_format = "NCHW" if data_format in ("NCL", "NCHW", "NCDHW") else "NHWC"
        self.weight = (None if weight_attr is False else self.create_parameter(
            (num_features,), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (num_features,), attr=ParamAttr._to_attr(bias_attr), is_bias=True))
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm2D(BatchNorm1D):
    _dims = 2

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats, name)


class BatchNorm3D(BatchNorm1D):
    _dims = 3


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups, self._epsilon = num_groups, epsilon
        self._data_format = data_format
        self.weight = (None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=ParamAttr._to_attr(bias_attr), is_bias=True))

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon, self._data_format)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


def _act_layer(fname, fn_kwargs=()):
    class _Act(Layer):
        def __init__(self, *args, name=None, **kwargs):
            super().__init__()
            self._args, self._kwargs = args, kwargs

        def forward(self, x):
            return getattr(F, fname)(x, *self._args, **self._kwargs)

    _Act.__name__ = "".join(p.capitalize() for p in fname.split("_"))
    return _Act


ReLU = _act_layer("relu")
ReLU6 = _act_layer("relu6")
GELU = _act_layer("gelu")
SiLU = _act_layer("silu")
Swish = _act_layer("swish")
Mish = _act_layer("mish")
Sigmoid = _act_layer("sigmoid")
Tanh = _act_layer("tanh")
LeakyReLU = _act_layer("leaky_relu")
ELU = _act_layer("elu")
CELU = _act_layer("celu")
SELU = _act_layer("selu")
Hardswish = _act_layer("hardswish")
Hardsigmoid = _act_layer("hardsigmoid")
Hardtanh = _act_layer("hardtanh")
Softplus = _act_layer("softplus")
Softshrink = _act_layer("softshrink")
Hardshrink = _act_layer("hardshrink")
Tanhshrink = _act_layer("tanhshrink")
Softsign = _act_layer("softsign")
LogSigmoid = _act_layer("log_sigmoid")
GLU = _act_layer("glu")


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ... import ops
        return ops.flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW", name=None):
        super().__init__()
        self._kw = dict(size=size, scale_factor=scale_factor, mode=mode,
                        align_corners=align_corners, data_format=data_format)

    def forward(self, x):
        return F.interpolate(x, **self._kw)


class Conv2D(Layer):
    """Conv with weight [out_c, in_c/groups, kh, kw]
    (reference: python/paddle/nn/layer/conv.py). Lowers to
    lax.conv_general_dilated which XLA maps onto the MXU."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._groups, self._data_format = groups, data_format
        fan_in = in_channels // groups * ks[0] * ks[1]
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *ks),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=I.KaimingUniform(fan_in=fan_in) if weight_attr is None else None)
        if bias_attr is False:
            self.bias = None
        else:
            bound = 1 / math.sqrt(fan_in)
            self.bias = self.create_parameter(
                (out_channels,), attr=ParamAttr._to_attr(bias_attr),
                default_initializer=I.Uniform(-bound, bound) if bias_attr is None else None,
                is_bias=bias_attr is not None)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._groups, self._data_format = groups, data_format
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, *ks),
            attr=ParamAttr._to_attr(weight_attr))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=ParamAttr._to_attr(bias_attr), is_bias=True))

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, self.bias, stride=self._stride,
                                  padding=self._padding, dilation=self._dilation,
                                  groups=self._groups, data_format=self._data_format)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self._kw = dict(kernel_size=kernel_size, stride=stride, padding=padding,
                        ceil_mode=ceil_mode, data_format=data_format)

    def forward(self, x):
        return F.max_pool2d(x, **self._kw)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__()
        self._kw = dict(kernel_size=kernel_size, stride=stride, padding=padding,
                        ceil_mode=ceil_mode, exclusive=exclusive,
                        divisor_override=divisor_override,
                        data_format=data_format)

    def forward(self, x):
        return F.avg_pool2d(x, **self._kw)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size, self._data_format = output_size, data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._r, self._data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self._r, self._data_format)


# ------------------------------------------------------------------- losses
class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                 name=None):
        super().__init__()
        self._kw = dict(weight=weight, ignore_index=ignore_index, reduction=reduction,
                        soft_label=soft_label, axis=axis, use_softmax=use_softmax,
                        label_smoothing=label_smoothing)

    def forward(self, input, label):
        return F.cross_entropy(input, label, **self._kw)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self._kw = dict(weight=weight, ignore_index=ignore_index, reduction=reduction)

    def forward(self, input, label):
        return F.nll_loss(input, label, **self._kw)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction, self.log_target = reduction, log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class BatchNorm(BatchNorm1D):
    """Rank-agnostic BatchNorm (reference: nn/layer/norm.py BatchNorm —
    the pre-2.0 API kept for compatibility; acts on dim 1)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 use_global_stats=False, name=None, **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            return F.relu(out)
        return out


class SyncBatchNorm(BatchNorm1D):
    """reference: nn/layer/norm.py SyncBatchNorm (cross-device stats via
    NCCL). TPU-native: under a jitted GSPMD step the batch axis is sharded
    over dp, so the plain mean/var reductions ALREADY lower to global
    collectives — synchronized stats fall out of the programming model
    rather than a special kernel. This subclass exists for API parity and
    for convert_sync_batchnorm."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively swap BatchNorm*D for SyncBatchNorm (reference
        classmethod of the same name)."""
        if isinstance(layer, BatchNorm1D) and not isinstance(
                layer, SyncBatchNorm):
            new = cls(layer._mean.shape[0], layer._momentum,
                      layer._epsilon,
                      data_format=layer._data_format)
            new._use_global_stats = layer._use_global_stats
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers["_mean"] = layer._mean
            new._buffers["_variance"] = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer
