"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

TPU-native design: the reference dispatches to a cuDNN fused RNN kernel
(paddle/phi/kernels/gpu/rnn_kernel.cu) or a per-step dygraph loop. Here every
(layer, direction) is ONE ``lax.scan`` over time — a single XLA while-loop
whose body is two MXU matmuls — recorded on the eager tape as one op
(``core/tensor.py::apply_op``), so it is differentiable eagerly and traces to
one fused loop under ``jit``. Variable-length batches use masked carries
instead of packed sequences (static shapes for XLA): steps at ``t >=
sequence_length`` keep the previous state and emit zero output, which
reproduces the reference's padded-sequence semantics for both directions.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from .. import functional as F
from .. import initializer as I
from ..layer import Layer
from ..param_attr import ParamAttr
from ...ops import manipulation as _manip


def _scan_rnn(name, step, n_state, x, states, params, sequence_length,
              is_reverse, time_major):
    """Run one scan over time for one (layer, direction).

    ``step(ps, x_t, states) -> (out_t, new_states)`` is a pure jax function;
    carries update only where ``t < sequence_length`` and masked steps emit
    zeros, so a reverse-direction scan walking t = T-1..0 consumes exactly the
    valid suffix-reversed sequence (reference semantics for padded batches).
    """
    n_par = len(params)
    has_len = sequence_length is not None

    def fn(xv, *rest, is_reverse=False, time_major=False):
        st = tuple(rest[:n_state])
        ps = tuple(rest[n_state:n_state + n_par])
        sl = rest[n_state + n_par] if has_len else None
        xs = xv if time_major else jnp.swapaxes(xv, 0, 1)  # [T, B, I]
        ts = jnp.arange(xs.shape[0])
        if is_reverse:
            xs, ts = xs[::-1], ts[::-1]

        def body(carry, xt_t):
            xt, t = xt_t
            out, new = step(ps, xt, carry)
            if sl is not None:
                m = (t < sl)[:, None]
                new = tuple(jnp.where(m, n, c) for n, c in zip(new, carry))
                out = jnp.where(m, out, jnp.zeros_like(out))
            return new, out

        final, outs = jax.lax.scan(body, st, (xs, ts))
        if is_reverse:
            outs = outs[::-1]
        if not time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return (outs,) + tuple(final)

    res = apply_op(name, fn, x, *states, *params,
                   *((sequence_length,) if has_len else ()),
                   is_reverse=is_reverse, time_major=time_major)
    return res[0], tuple(res[1:])


class RNNCellBase(Layer):
    """Base for single-step recurrent cells
    (reference: python/paddle/nn/layer/rnn.py::RNNCellBase)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shapes = self.state_shape
        if isinstance(shapes[0], (tuple, list)):
            return tuple(
                Tensor(jnp.full((batch,) + tuple(s), init_value,
                                dtype or jnp.float32), stop_gradient=True)
                for s in shapes)
        return Tensor(jnp.full((batch,) + tuple(shapes), init_value,
                               dtype or jnp.float32), stop_gradient=True)

    def _make_params(self, input_size, hidden_size, n_gates,
                     weight_ih_attr=None, weight_hh_attr=None,
                     bias_ih_attr=None, bias_hh_attr=None):
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        g = n_gates * hidden_size
        self.weight_ih = self.create_parameter(
            (g, input_size), attr=ParamAttr._to_attr(weight_ih_attr),
            default_initializer=None if weight_ih_attr else init)
        self.weight_hh = self.create_parameter(
            (g, hidden_size), attr=ParamAttr._to_attr(weight_hh_attr),
            default_initializer=None if weight_hh_attr else init)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter(
            (g,), attr=ParamAttr._to_attr(bias_ih_attr), is_bias=True,
            default_initializer=None if bias_ih_attr else init)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter(
            (g,), attr=ParamAttr._to_attr(bias_hh_attr), is_bias=True,
            default_initializer=None if bias_hh_attr else init)

    def _param_tuple(self):
        return (self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)

    def _forward_one_step(self, inputs, states):
        st = states if isinstance(states, (tuple, list)) else (states,)
        n_state = len(st)
        step = self._step_fn

        def fn(xv, *rest):
            out, new = step(tuple(rest[n_state:]), xv, tuple(rest[:n_state]))
            return (out,) + tuple(new)

        res = apply_op(self._op_name, fn, inputs, *st, *self._param_tuple())
        new = tuple(res[1:])
        return res[0], (new if len(new) > 1 else new[0])


def _gates(ps, xt, h):
    w_ih, w_hh, b_ih, b_hh = ps
    g = xt @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        g = g + b_ih
    if b_hh is not None:
        g = g + b_hh
    return g


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh)."""

    _op_name = "simple_rnn_cell"

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError(
                f"activation must be tanh or relu, got {activation}")
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        self._make_params(input_size, hidden_size, 1, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)
        # static activation choice baked into the jax step
        self._step_fn = (SimpleRNNCell._step_tanh if activation == "tanh"
                         else SimpleRNNCell._step_relu)

    @staticmethod
    def _step_tanh(ps, xt, states):
        h = jnp.tanh(_gates(ps, xt, states[0]))
        return h, (h,)

    @staticmethod
    def _step_relu(ps, xt, states):
        h = jax.nn.relu(_gates(ps, xt, states[0]))
        return h, (h,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out, new = self._forward_one_step(inputs, states)
        return out, new

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        return (f"{self.input_size}, {self.hidden_size}"
                + (f", activation={self.activation}"
                   if self.activation != "tanh" else ""))


class LSTMCell(RNNCellBase):
    """Gate order [i, f, g, o] matching the reference (and cuDNN/torch)."""

    _op_name = "lstm_cell"

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self._make_params(input_size, hidden_size, 4, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)
        self._step_fn = LSTMCell._jax_step

    @staticmethod
    def _jax_step(ps, xt, states):
        h, c = states
        i, f, g, o = jnp.split(_gates(ps, xt, h), 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c2 = f * c + i * jnp.tanh(g)
        h2 = o * jnp.tanh(c2)
        return h2, (h2, c2)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        return self._forward_one_step(inputs, states)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class GRUCell(RNNCellBase):
    """Gate order [r, z, c]; h' = z * h + (1 - z) * c (reference formula)."""

    _op_name = "gru_cell"

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self._make_params(input_size, hidden_size, 3, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)
        self._step_fn = GRUCell._jax_step

    @staticmethod
    def _jax_step(ps, xt, states):
        w_ih, w_hh, b_ih, b_hh = ps
        h = states[0]
        xg = xt @ w_ih.T + (b_ih if b_ih is not None else 0.0)
        hg = h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
        xr, xz, xc = jnp.split(xg, 3, axis=-1)
        hr, hz, hc = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        c = jnp.tanh(xc + r * hc)
        h2 = (h - c) * z + c
        return h2, (h2,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out, new = self._forward_one_step(inputs, states)
        return out, new

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class RNN(Layer):
    """Wrap a cell into a scanner over time
    (reference: python/paddle/nn/layer/rnn.py::RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            initial_states = self.cell.get_initial_states(
                inputs, batch_dim_idx=1 if self.time_major else 0)
        st = (initial_states if isinstance(initial_states, (tuple, list))
              else (initial_states,))
        outs, final = _scan_rnn(
            f"{self.cell._op_name}_scan", self.cell._step_fn, len(st),
            inputs, st, self.cell._param_tuple(), sequence_length,
            self.is_reverse, self.time_major)
        return outs, (final if len(final) > 1 else final[0])


class BiRNN(Layer):
    """Run two cells over opposite directions, concat outputs
    (reference: python/paddle/nn/layer/rnn.py::BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw, self.cell_bw = cell_fw, cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            initial_states = (None, None)
        rnn_fw = RNN(self.cell_fw, False, self.time_major)
        rnn_bw = RNN(self.cell_bw, True, self.time_major)
        out_fw, st_fw = rnn_fw(inputs, initial_states[0], sequence_length)
        out_bw, st_bw = rnn_bw(inputs, initial_states[1], sequence_length)
        return _manip.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer, optionally bidirectional stack; one scan per
    (layer, direction). Final states stack as [L * D, B, H] in layer-major,
    direction-minor order (reference layout)."""

    _CELL = None
    _N_STATE = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None,
                 **cell_kwargs):
        super().__init__()
        if direction in ("bidirectional", "bidirect"):
            self.num_directions = 2
        elif direction == "forward":
            self.num_directions = 1
        else:
            raise ValueError(
                f"direction must be forward or bidirect, got {direction!r}")
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        attrs = dict(weight_ih_attr=weight_ih_attr,
                     weight_hh_attr=weight_hh_attr,
                     bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
        from ..layer import LayerList
        self._cells = LayerList()
        for layer in range(num_layers):
            in_size = (input_size if layer == 0
                       else hidden_size * self.num_directions)
            for _ in range(self.num_directions):
                self._cells.append(
                    type(self)._CELL(in_size, hidden_size, **cell_kwargs,
                                     **attrs))

    def _zeros_state(self, batch, dtype):
        n = self.num_layers * self.num_directions
        z = Tensor(jnp.zeros((n, batch, self.hidden_size), dtype),
                   stop_gradient=True)
        return z

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch = inputs.shape[1 if self.time_major else 0]
        dtype = inputs._value.dtype
        if initial_states is None:
            if self._N_STATE == 2:
                initial_states = (self._zeros_state(batch, dtype),
                                  self._zeros_state(batch, dtype))
            else:
                initial_states = self._zeros_state(batch, dtype)
        init = (initial_states if isinstance(initial_states, (tuple, list))
                else (initial_states,))

        x = inputs
        finals = []  # one tuple of states per (layer, direction)
        for layer in range(self.num_layers):
            outs_dir = []
            for d in range(self.num_directions):
                idx = layer * self.num_directions + d
                cell = self._cells[idx]
                st = tuple(s[idx] for s in init)
                outs, final = _scan_rnn(
                    f"{cell._op_name}_scan", cell._step_fn, self._N_STATE,
                    x, st,
                    cell._param_tuple(), sequence_length, d == 1,
                    self.time_major)
                outs_dir.append(outs)
                finals.append(final)
            x = (outs_dir[0] if self.num_directions == 1
                 else _manip.concat(outs_dir, axis=-1))
            if self.dropout > 0.0 and layer < self.num_layers - 1:
                x = F.dropout(x, p=self.dropout, training=self.training)

        stacked = tuple(
            _manip.stack([f[k] for f in finals], axis=0)
            for k in range(self._N_STATE))
        return x, (stacked if self._N_STATE > 1 else stacked[0])

    def extra_repr(self):
        s = f"{self.input_size}, {self.hidden_size}"
        if self.num_layers != 1:
            s += f", num_layers={self.num_layers}"
        if self.num_directions == 2:
            s += ", direction=bidirect"
        return s


class SimpleRNN(_RNNBase):
    _CELL = SimpleRNNCell
    _N_STATE = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation, **kwargs)


class LSTM(_RNNBase):
    _CELL = LSTMCell
    _N_STATE = 2


class GRU(_RNNBase):
    _CELL = GRUCell
    _N_STATE = 1
