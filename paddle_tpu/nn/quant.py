"""paddle.nn.quant — weight-only quantization ops (reference:
python/paddle/nn/quant/quantized_linear.py: weight_quantize,
weight_dequantize, weight_only_linear, llm_int8_linear over the
weight_only_gemm / llm.int8 CUDA kernels).

TPU-native: quantized weights live in HBM at 1/2 (int8) or 1/4 (int4)
the bytes; the dequantize folds into the MXU feed (XLA fuses convert +
per-channel scale into the matmul). Core implementations are shared
with :mod:`paddle_tpu.incubate.nn.functional` — one math, two namespaces
(the reference ships both)."""

from __future__ import annotations

import jax.numpy as jnp

from ..incubate.nn.functional import weight_only_linear, weight_quantize

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear", "QuantizedLinear", "quantize_linears"]


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float32", group_size=-1):
    """Inverse of :func:`weight_quantize` — back to the dense weight
    (same unpack/scale helper as the serving matmul, so the packing
    convention cannot drift between them)."""
    from ..core.tensor import Tensor, _val
    from ..incubate.nn.functional import _dequantize_weight
    wf = _dequantize_weight(_val(x), _val(scale), algo, group_size,
                            jnp.dtype(out_dtype))
    return Tensor(wf, stop_gradient=True)


from ..core.tensor import Tensor
from .layer import Layer


class QuantizedLinear(Layer):
    """Weight-only-quantized drop-in for ``nn.Linear`` (the serving path;
    reference: PaddleNLP's WeightOnlyLinear over the weight_only_gemm
    kernel). The int8/int4 weight and its per-channel scales are BUFFERS
    (inference-only, no gradients); the matmul dequantizes into the MXU
    feed, so HBM traffic per decode step halves (int8) or quarters
    (int4) vs bf16 — decode is weight-bandwidth-bound, so this moves the
    single-stream roofline by the same factor."""

    def __init__(self, in_features, out_features, algo="weight_only_int8",
                 group_size=-1, has_bias=True):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self._algo = algo
        self._group_size = group_size
        if "int4" in algo and in_features % 2:
            raise ValueError(f"int4 packing needs an even in_features, "
                             f"got {in_features}")
        if group_size > 0 and in_features % group_size:
            raise ValueError(f"in_features {in_features} not divisible by "
                             f"group_size {group_size}")
        packed_k = in_features if "int8" in algo else in_features // 2
        scale_shape = ((in_features // group_size, out_features)
                       if group_size > 0 else (out_features,))
        self.register_buffer("quant_weight", Tensor(
            jnp.zeros((packed_k, out_features), jnp.int8),
            stop_gradient=True))
        self.register_buffer("weight_scale", Tensor(
            jnp.zeros(scale_shape, jnp.float32), stop_gradient=True))
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return weight_only_linear(
            x, self.quant_weight, self.bias, self.weight_scale,
            weight_dtype="int8" if "int8" in self._algo else "int4",
            group_size=self._group_size)

    def extra_repr(self):
        return (f"in_features={self._in_features}, "
                f"out_features={self._out_features}, algo={self._algo}")

    @staticmethod
    def from_linear(linear, algo="weight_only_int8", group_size=-1,
                    _shared=None):
        """Quantize an existing ``nn.Linear``'s weights into a
        QuantizedLinear (bias carried over by value).

        Streaming materialization: a Linear built under ``LazyGuard``
        (meta weight, zero bytes) is materialized HERE — its recorded
        initializer runs, the bf16 weight is quantized on device, and the
        source weight is returned to its meta state so the bf16 frees
        immediately. Peak HBM while quantizing a LazyGuard model is thus
        the int8 weights accumulated so far plus ONE layer's bf16 weight
        — how a 7B model (13.4 GB bf16) becomes int8 (6.7 GB) on a single
        16 GB v5e chip without ever holding the dense model."""
        from ..framework.lazy import is_lazy, mark_consumed, \
            materialize_parameter

        q = QuantizedLinear(linear._in_features, linear._out_features,
                            algo=algo, group_size=group_size,
                            has_bias=linear.bias is not None)
        if _shared is not None:
            # weight already quantized via another Linear sharing the
            # same Parameter (quantize_linears tying): alias the SAME
            # buffer Tensors so the tie survives quantization
            q.register_buffer("quant_weight", _shared[0])
            q.register_buffer("weight_scale", _shared[1])
            if linear.bias is not None:
                materialize_parameter(linear.bias)
                q.bias.set_value(linear.bias)
            return q
        lazy_src = is_lazy(linear.weight)
        if lazy_src:
            meta = linear.weight._value  # ShapeDtypeStruct, re-set below
            materialize_parameter(linear.weight)
        qw, scale = weight_quantize(linear.weight, algo=algo,
                                    group_size=group_size)
        q.quant_weight.set_value(qw)
        q.weight_scale.set_value(scale)
        if linear.bias is not None:
            materialize_parameter(linear.bias)
            q.bias.set_value(linear.bias)
        if lazy_src:
            # free the one-layer bf16 now; the source Linear is dead —
            # mark it so a later materialize() fails loudly, not silently
            linear.weight._value = meta
            mark_consumed(linear.weight)
        return q


def quantize_linears(layer, algo="weight_only_int8", group_size=-1,
                     skip=()):
    """Replace every plain ``nn.Linear`` sublayer of ``layer`` (exact
    type match — parallel/quantized variants untouched) with a
    ``QuantizedLinear`` initialized from its weights. In-place; returns
    ``layer``. ``skip``: attribute names to leave in full precision
    (e.g. ("lm_head",)). int4 requires even in_features; offending
    layers are left unquantized."""
    from .layers.common import Linear

    todo = []
    for parent in layer.sublayers(include_self=True):
        for name, sub in list(parent._sub_layers.items()):
            if type(sub) is Linear and name not in skip:
                if "int4" in algo and sub._in_features % 2:
                    continue
                todo.append((parent, name, sub))
    made = {}     # id(Linear) -> QuantizedLinear: a shared Linear
    # instance quantizes ONCE and stays shared; a shared weight
    # PARAMETER across distinct Linears quantizes once and the second
    # QuantizedLinear aliases the same int8 buffers — either way the tie
    # survives instead of untying into duplicate copies (and, on the
    # lazy streaming path, crashing on the second consume of the weight)
    wcache = {}   # id(weight Parameter) -> (quant_weight, weight_scale)
    for parent, name, sub in todo:
        q = made.get(id(sub))
        if q is None:
            shared = wcache.get(id(sub.weight))
            q = made[id(sub)] = QuantizedLinear.from_linear(
                sub, algo, group_size, _shared=shared)
            if shared is None:
                wcache[id(sub.weight)] = (q.quant_weight, q.weight_scale)
        setattr(parent, name, q)
    return layer


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold: float = 6.0):
    """reference: llm.int8 (Dettmers et al.) — activation outliers above
    ``threshold`` compute in full precision, the rest through the int8
    weight. On TPU the weight already dequantizes into the matmul, so
    the mixed decomposition reduces to the same dequantized GEMM — kept
    for API parity; ``threshold`` only gates which rows WOULD take the
    outlier path in the reference kernel."""
    return weight_only_linear(x, weight, bias=bias,
                              weight_scale=weight_scale,
                              weight_dtype="int8")
