"""paddle.nn.quant — weight-only quantization ops (reference:
python/paddle/nn/quant/quantized_linear.py: weight_quantize,
weight_dequantize, weight_only_linear, llm_int8_linear over the
weight_only_gemm / llm.int8 CUDA kernels).

TPU-native: quantized weights live in HBM at 1/2 (int8) or 1/4 (int4)
the bytes; the dequantize folds into the MXU feed (XLA fuses convert +
per-channel scale into the matmul). Core implementations are shared
with :mod:`paddle_tpu.incubate.nn.functional` — one math, two namespaces
(the reference ships both)."""

from __future__ import annotations

import jax.numpy as jnp

from ..incubate.nn.functional import weight_only_linear, weight_quantize

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"]


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float32", group_size=-1):
    """Inverse of :func:`weight_quantize` — back to the dense weight
    (same unpack/scale helper as the serving matmul, so the packing
    convention cannot drift between them)."""
    from ..core.tensor import Tensor, _val
    from ..incubate.nn.functional import _dequantize_weight
    wf = _dequantize_weight(_val(x), _val(scale), algo, group_size,
                            jnp.dtype(out_dtype))
    return Tensor(wf, stop_gradient=True)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold: float = 6.0):
    """reference: llm.int8 (Dettmers et al.) — activation outliers above
    ``threshold`` compute in full precision, the rest through the int8
    weight. On TPU the weight already dequantizes into the matmul, so
    the mixed decomposition reduces to the same dequantized GEMM — kept
    for API parity; ``threshold`` only gates which rows WOULD take the
    outlier path in the reference kernel."""
    return weight_only_linear(x, weight, bias=bias,
                              weight_scale=weight_scale,
                              weight_dtype="int8")
