"""nn functional ops (reference: python/paddle/nn/functional/).

All implemented directly over jax/XLA; the fused hot ops (flash attention,
fused rms_norm, …) live in paddle_tpu/incubate/nn/functional.py as Pallas
kernels with these as reference fallbacks.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtype import to_jax_dtype
from ..core.tensor import Tensor, apply_op, _val
from ..framework.random import next_key

# ------------------------------------------------------------- activations


def _unary(op_name, jfn):
    def op(x, name=None):
        return apply_op(op_name, jfn, x)

    op.__name__ = op_name
    return op


relu = _unary("relu", jax.nn.relu)
relu6 = _unary("relu6", jax.nn.relu6)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
tanh = _unary("tanh", jnp.tanh)
silu = _unary("silu", jax.nn.silu)
swish = silu
mish = _unary("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
hardswish = _unary("hardswish", jax.nn.hard_swish)
hardsigmoid = _unary("hardsigmoid", lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0))
tanhshrink = _unary("tanhshrink", lambda x: x - jnp.tanh(x))
softsign = _unary("softsign", jax.nn.soft_sign)
selu_ = _unary("selu", jax.nn.selu)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def gelu(x, approximate=False, name=None):
    return apply_op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda a: jax.nn.elu(a, alpha), x)


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda a: jax.nn.celu(a, alpha), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return apply_op("prelu", fn, x, weight)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op("hardtanh", lambda a: jnp.clip(a, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply_op("hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        "softplus",
        lambda a: jnp.where(beta * a > threshold, a, jax.nn.softplus(beta * a) / beta), x)


def log_sigmoid(x, name=None):
    return apply_op("log_sigmoid", jax.nn.log_sigmoid, x)


def softmax(x, axis=-1, dtype=None, name=None):
    jd = to_jax_dtype(dtype)
    def fn(a):
        if jd is not None:
            a = a.astype(jd)
        return jax.nn.softmax(a, axis=axis)
    return apply_op("softmax", fn, x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    jd = to_jax_dtype(dtype)
    def fn(a):
        if jd is not None:
            a = a.astype(jd)
        return jax.nn.log_softmax(a, axis=axis)
    return apply_op("log_softmax", fn, x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    g = jax.random.gumbel(next_key(), tuple(_val(x).shape), jnp.result_type(_val(x)))
    def fn(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y).at[
                tuple(jnp.indices(y.shape)[i] if i != (axis % y.ndim) else idx
                      for i in range(y.ndim))].set(1.0)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y
    return apply_op("gumbel_softmax", fn, x)


def glu(x, axis=-1, name=None):
    def fn(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return apply_op("glu", fn, x)


def swiglu(x, y=None, name=None):
    """SwiGLU: silu(x) * y (fused gate for Llama-style FFN).
    Reference analogue: paddle.incubate.nn.functional.swiglu."""
    if y is None:
        def fn(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2
        return apply_op("swiglu", fn, x)
    return apply_op("swiglu", lambda a, b: jax.nn.silu(a) * b, x, y)


# ------------------------------------------------------------------ linear
def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Weight layout [in, out] (paddle convention —
    reference: python/paddle/nn/functional/common.py::linear)."""
    if bias is None:
        return apply_op("linear", lambda a, w: a @ w, x, weight)
    return apply_op("linear", lambda a, w, b: a @ w + b, x, weight, bias)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    from .. import flags

    idx = _val(x)
    # one snapshot at the trace boundary (tracecheck TRC001): a bare
    # get_flag here would bake per-trace and bypass program-cache keys
    snap = flags.snapshot(("embedding_matmul_grad",))
    mode = snap.embedding_matmul_grad
    if mode not in ("auto", "on", "off"):
        raise ValueError(
            f"FLAGS_embedding_matmul_grad must be 'auto', 'on' or 'off', "
            f"got {mode!r}")
    matmul_grad = (mode == "on"
                   or (mode == "auto" and flags.is_tpu_backend()))
    if padding_idx is not None and padding_idx < 0:
        # paddle semantics: negative padding_idx counts from the end
        padding_idx = int(weight.shape[0]) + int(padding_idx)

    def take(w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    if not matmul_grad:
        return apply_op("embedding", take, weight)

    # custom vjp: d_w as a one-hot matmul on the MXU. jnp.take's native
    # vjp is a scatter-add, which XLA lowers to a serialized while loop
    # on TPU — PROFILE_r05 showed those loops (carrying the whole
    # bf16[50304,1024] table) among the top ops of the 345M step. The
    # one-hot contraction is the same math (sum of grads per token id),
    # runs as one matmul, and accumulates in f32 for free on the MXU.
    @jax.custom_vjp
    def lookup(w):
        return take(w)

    def fwd(w):
        return take(w), w.shape[0]

    def bwd(vocab, g):
        flat_idx = idx.reshape(-1)
        flat_g = g.reshape(-1, g.shape[-1])
        if padding_idx is not None:
            keep = (flat_idx != padding_idx)[:, None]
            flat_g = jnp.where(keep, flat_g, 0.0)
        oh = jax.nn.one_hot(flat_idx, vocab, dtype=flat_g.dtype)
        d_w = jnp.matmul(oh.T, flat_g,
                         preferred_element_type=jnp.float32)
        return (d_w.astype(g.dtype),)

    lookup.defvjp(fwd, bwd)
    return apply_op("embedding", lookup, weight)


def one_hot(x, num_classes, name=None):
    return Tensor(jax.nn.one_hot(_val(x), num_classes))


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi:
            out = out + bi[0]
        return out
    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply_op("bilinear", fn, *args)


# -------------------------------------------------------------- normalization
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))

    def fn(a, *wb):
        # stats in fp32, output (and affine params) in the input dtype:
        # keeps a bf16 residual stream bf16 under amp (see amp/auto_cast.py
        # BLACK_LIST note) without giving up fp32 mean/var numerics
        wb = tuple(w.astype(a.dtype) for w in wb)
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]; i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply_op("layer_norm", fn, *args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (reference: paddle/phi/kernels/gpu/rms_norm_kernel.cu →
    here a single XLA fusion; Pallas variant in incubate)."""
    def fn(a, *w):
        h = a.astype(jnp.float32)
        var = jnp.mean(h * h, axis=-1, keepdims=True)
        out = (h * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        if w:
            out = out * w[0].astype(a.dtype)
        return out
    args = (x,) + ((weight,) if weight is not None else ())
    return apply_op("rms_norm", fn, *args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    ch_axis = 1 if data_format.startswith("NC") else -1

    def stats_shape(a):
        s = [1] * a.ndim
        s[ch_axis] = a.shape[ch_axis]
        return s

    rm, rv = _val(running_mean), _val(running_var)
    if training and not use_global_stats:
        v = _val(x)
        axes = tuple(i for i in range(v.ndim) if i != (ch_axis % v.ndim))
        batch_mean = jnp.mean(v.astype(jnp.float32), axis=axes)
        batch_var = jnp.var(v.astype(jnp.float32), axis=axes)
        # update running stats in place (paddle semantics)
        running_mean._value = (momentum * rm + (1 - momentum) * batch_mean).astype(rm.dtype)
        running_var._value = (momentum * rv + (1 - momentum) * batch_var).astype(rv.dtype)
        mean_, var_ = batch_mean, batch_var
    else:
        mean_, var_ = rm, rv

    def fn(a, *wb):
        wb = tuple(w.astype(a.dtype) for w in wb)
        shape = stats_shape(a)
        out = (a - mean_.reshape(shape)) * jax.lax.rsqrt(var_.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out.astype(a.dtype)

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply_op("batch_norm", fn, *args)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW", name=None):
    def fn(a, *wb):
        wb = tuple(w.astype(a.dtype) for w in wb)
        if not data_format.startswith("NC"):
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        rest = a_t.shape[2:]
        g = a_t.reshape(n, num_groups, c // num_groups, *rest).astype(jnp.float32)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a_t.shape).astype(a.dtype)
        shape = [1, c] + [1] * len(rest)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if not data_format.startswith("NC"):
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply_op("group_norm", fn, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    def fn(a, *wb):
        wb = tuple(w.astype(a.dtype) for w in wb)
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)).astype(a.dtype)
        c = a.shape[1]
        shape = [1, c] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply_op("instance_norm", fn, *args)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return apply_op(
        "normalize",
        lambda a: a / jnp.maximum(
            jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p), epsilon), x)


# ----------------------------------------------------------------- dropout
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training and p > 0.0:
            return apply_op("dropout", lambda a: (a * (1.0 - p)).astype(a.dtype), x)
        return x if isinstance(x, Tensor) else Tensor(x)
    v = _val(x)
    shape = list(v.shape)
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(_dropout_key(), 1.0 - p, tuple(shape))

    def fn(a):
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply_op("dropout", fn, x)


def _dropout_key():
    """Dropout keys respect the TP-aware RNGStatesTracker when one is active
    (reference: fleet/meta_parallel/parallel_layers/random.py)."""
    from ..distributed.fleet import random as fleet_random
    tracker = fleet_random.get_rng_state_tracker()
    if tracker.active_state is not None:
        return tracker.next_key()
    return next_key()


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale_ = 1.0507009873554805
    alpha_p = -alpha * scale_
    keep = jax.random.bernoulli(next_key(), 1.0 - p, tuple(_val(x).shape))
    a = (1.0 / math.sqrt((1.0 - p) * (1.0 + p * alpha_p ** 2)))
    b = -a * alpha_p * p
    return apply_op("alpha_dropout",
                    lambda v: (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype), x)


# ------------------------------------------------------------------- losses
def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    lbl = _val(label)

    def fn(logits, *w):
        lg = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) if use_softmax \
            else jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-30, None))
        if soft_label:
            tgt = lbl.astype(jnp.float32)
            loss = -jnp.sum(tgt * lg, axis=axis)
        else:
            l = lbl
            if l.ndim == lg.ndim:
                l = jnp.squeeze(l, axis=axis)
            nclass = lg.shape[axis]
            if label_smoothing > 0.0:
                onehot = jax.nn.one_hot(l, nclass, axis=axis, dtype=jnp.float32)
                tgt = onehot * (1 - label_smoothing) + label_smoothing / nclass
                loss = -jnp.sum(tgt * lg, axis=axis)
            else:
                loss = -jnp.take_along_axis(
                    lg, jnp.expand_dims(l, axis).astype(jnp.int32), axis=axis
                ).squeeze(axis)
            mask = (l != ignore_index)
            loss = jnp.where(mask, loss, 0.0)
            if w:
                loss = loss * jnp.take(w[0], jnp.clip(l, 0, nclass - 1))
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
                if w:
                    denom = jnp.maximum(jnp.sum(
                        jnp.where(mask, jnp.take(w[0], jnp.clip(l, 0, nclass - 1)), 0.0)), 1e-12)
                return jnp.sum(loss) / denom
            if reduction == "sum":
                return jnp.sum(loss)
            return loss
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    args = (input,) + ((weight,) if weight is not None else ())
    return apply_op("cross_entropy", fn, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(axis) if not soft_label else loss
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    lbl = _val(label)
    def fn(lg, *w):
        loss = -jnp.take_along_axis(lg, lbl[..., None].astype(jnp.int32), axis=-1).squeeze(-1)
        mask = lbl != ignore_index
        loss = jnp.where(mask, loss, 0.0)
        if w:
            loss = loss * jnp.take(w[0], jnp.clip(lbl, 0, lg.shape[-1] - 1))
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    args = (input,) + ((weight,) if weight is not None else ())
    return apply_op("nll_loss", fn, *args)


def mse_loss(input, label, reduction="mean", name=None):
    def fn(a, b):
        loss = (a - b) ** 2
        return _reduce_loss(loss, reduction)
    return apply_op("mse_loss", fn, input, label)


def l1_loss(input, label, reduction="mean", name=None):
    def fn(a, b):
        return _reduce_loss(jnp.abs(a - b), reduction)
    return apply_op("l1_loss", fn, input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce_loss(loss, reduction)
    return apply_op("smooth_l1_loss", fn, input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(a, b, *w):
        a = jnp.clip(a, 1e-12, 1 - 1e-12)
        loss = -(b * jnp.log(a) + (1 - b) * jnp.log1p(-a))
        if w:
            loss = loss * w[0]
        return _reduce_loss(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply_op("bce", fn, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def fn(a, b, *rest):
        mx = jnp.maximum(a, 0)
        loss = mx - a * b + jnp.log1p(jnp.exp(-jnp.abs(a)))
        i = 0
        if pos_weight is not None:
            pw = rest[i]; i += 1
            loss = loss * (b * (pw - 1) + 1)
        if weight is not None:
            loss = loss * rest[i]
        return _reduce_loss(loss, reduction)
    args = [logit, label]
    if pos_weight is not None:
        args.append(pos_weight)
    if weight is not None:
        args.append(weight)
    return apply_op("bce_logits", fn, *args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(a, b):
        tgt = jnp.exp(b) if log_target else b
        loss = tgt * ((b if log_target else jnp.log(jnp.clip(b, 1e-30, None))) - a)
        if reduction == "batchmean":
            return jnp.sum(loss) / a.shape[0]
        return _reduce_loss(loss, reduction)
    return apply_op("kl_div", fn, input, label)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)
    return apply_op("cosine_similarity", fn, x1, x2)


def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


# ---------------------------------------------------------------- attention
def cached_scaled_dot_product_attention(query, key, value, k_cache, v_cache,
                                        offset):
    """Decode-phase attention (reference: the masked-MHA cache branch of
    paddle/fluid/operators/fused/fused_multi_transformer_op.cu): write the
    new key/value chunk (B, S, Hkv, D) into the static ring-buffer caches
    (B, T, Hkv, D) at sequence position ``offset``, then attend ``query``
    (B, S, H, D; GQA allowed) causally against the written prefix.

    Returns ``(out, k_cache, v_cache)`` — out (B, S, H, D), caches updated.
    ``offset`` may be a python int or a traced scalar; shapes stay static so
    one compilation serves every decode step."""
    from ..kernels.decode_attention import cached_attention, update_kv_cache

    def fn(qv, knv, vnv, kcv, vcv, off):
        kcv, vcv = update_kv_cache(kcv, vcv, knv, vnv, off)
        out = cached_attention(qv, kcv, vcv,
                               jnp.asarray(off, jnp.int32) + qv.shape[1])
        return out, kcv, vcv

    return apply_op("cached_sdpa", fn, query, key, value, k_cache, v_cache,
                    offset)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """reference: python/paddle/nn/functional/flash_attention.py
    ``flash_attention`` — [B, S, H, D] layout, returns ``(out, softmax)``
    with softmax None unless requested (the fused kernel never
    materializes it; ``return_softmax=True`` raises like the reference
    does on backends without the debug path)."""
    if return_softmax:
        raise NotImplementedError(
            "return_softmax requires materializing the (S, S) matrix the "
            "flash kernel exists to avoid — use plain "
            "scaled_dot_product_attention for debugging")
    if dropout and training:   # inference dropout is a no-op, like the ref
        raise NotImplementedError("attention dropout is not folded into "
                                  "the TPU flash kernel")
    out = scaled_dot_product_attention(query, key, value, is_causal=causal,
                                       training=training)
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """reference: flash_attn_unpadded (the varlen/packed form over
    FlashAttnUnpaddedKernel). Packed [total_tokens, H, D] with cumulative
    sequence boundaries. TPU-native: the packed batch becomes ONE flash
    call with SEGMENT IDS — the kernel's block-skip masks cross-sequence
    attention, no unpadding/repacking kernels needed. Causal masking uses
    LOCAL per-sequence positions; the kernel path serves the dominant
    self-attention case (identical q/k boundaries), other layouts take
    the dense segment-masked path."""
    if return_softmax:
        raise NotImplementedError("return_softmax: see flash_attention")
    if dropout and training:
        raise NotImplementedError("attention dropout is not folded into "
                                  "the TPU flash kernel")
    from .. import flags
    from ..kernels.flash_attention import flash_attention_bshd

    cu_q = _val(cu_seqlens_q)
    cu_k = _val(cu_seqlens_k)
    try:   # concrete boundaries: is this a self-attention pack?
        same_pack = np.array_equal(np.asarray(cu_q), np.asarray(cu_k))
    except Exception:   # traced inside jit: assume the dominant layout
        same_pack = True
    snap = flags.snapshot(("use_pallas",))
    kernel_ok = (snap.use_pallas and flags.is_tpu_backend()
                 and (same_pack or not causal))

    def fn(qv, kv, vv, cq, ck):
        tq = qv.shape[0]
        tk = kv.shape[0]
        # token i belongs to the sequence whose boundary interval holds i
        seg_q = jnp.searchsorted(cq, jnp.arange(tq), side="right")[None, :]
        seg_k = jnp.searchsorted(ck, jnp.arange(tk), side="right")[None, :]
        sc = scale if scale is not None else 1.0 / math.sqrt(qv.shape[-1])
        if kernel_ok:
            # contiguous SELF-attention packing: global causal order ==
            # per-sequence local order, so global-causal + segment mask
            # is exact
            try:
                out = flash_attention_bshd(
                    qv[None], kv[None], vv[None], segment_ids=seg_q,
                    kv_segment_ids=seg_k, causal=causal, sm_scale=sc)
                return out[0]
            except NotImplementedError:
                pass   # packed total not block-divisible
        h, hkv = qv.shape[1], kv.shape[1]
        kx = jnp.repeat(kv, h // hkv, axis=1) if hkv != h else kv
        vx = jnp.repeat(vv, h // hkv, axis=1) if hkv != h else vv
        s = jnp.einsum("qhd,khd->hqk", qv.astype(jnp.float32),
                       kx.astype(jnp.float32)) * sc
        mask = (seg_q[0][:, None] == seg_k[0][None, :])
        if causal:
            # LOCAL positions: token index minus its sequence's start
            start_q = jnp.concatenate([jnp.zeros((1,), cq.dtype),
                                       cq])[seg_q[0]]
            start_k = jnp.concatenate([jnp.zeros((1,), ck.dtype),
                                       ck])[seg_k[0]]
            loc_q = jnp.arange(tq) - start_q
            loc_k = jnp.arange(tk) - start_k
            mask &= loc_q[:, None] >= loc_k[None, :]
        s = jnp.where(mask[None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        any_vis = jnp.any(mask, axis=-1)[None, :, None]
        p = jnp.where(any_vis, p, 0.0)
        return jnp.einsum("hqk,khd->qhd", p,
                          vx.astype(jnp.float32)).astype(qv.dtype)

    out = apply_op("flash_attn_unpadded", fn, query, key, value, cu_q, cu_k)
    return out, None


def paged_scaled_dot_product_attention(query, key, value, state):
    """Paged (block-table) variant of the decode attention (reference:
    block_multihead_attention's two phases). ``state`` is a per-layer
    :class:`~paddle_tpu.kernels.paged_attention.PagedDecodeState` or —
    for chunked prefill — a ``PagedChunkState``; the state TYPE routes
    the S > 1 phase statically at trace time.

    Prefill (S > 1, PagedDecodeState, empty cache): the prompt attends
    causally to ITSELF (no cache read needed), then its k/v write into
    the pool pages.
    Chunked prefill (S > 1, PagedChunkState, B = 1): the chunk writes at
    positions ``seq_lens .. seq_lens+S-1`` and attends to the
    already-written prefix PLUS itself causally, reading the pool
    through the block table page by page (``paged_chunk_attention`` on
    chip, its copy-free XLA twin elsewhere) — no gathered per-sequence
    view is materialized. Pad positions past the block table are dropped — but
    the returned state's ``seq_lens`` still advance by the full static
    S, so a PADDED final chunk overcounts by its pad tail: the driver
    owns the true lengths (see the PagedChunkState length contract).
    Decode (S == 1): the token writes at position ``seq_lens`` and
    attends against the pool through the Pallas block-table kernel (XLA
    gather fallback when pallas is off). Returns ``(out, new_state)``."""
    from .. import flags
    from ..kernels.decode_attention import cached_attention
    from ..kernels.paged_attention import (PagedChunkState, QuantizedPages,
                                           paged_attention,
                                           paged_attention_xla,
                                           paged_chunk_attention,
                                           paged_chunk_attention_xla,
                                           write_paged_kv,
                                           write_paged_prompt,
                                           write_paged_prompt_at)

    use_pallas = (flags.snapshot(("use_pallas",)).use_pallas
                  and flags.is_tpu_backend())
    chunked = isinstance(state, PagedChunkState)

    # a quantized pool reaches here as a NamedTuple whose FIELDS were
    # Tensor-wrapped by functional_call's tree walk (the tuple itself is
    # a pytree node, not a leaf) — unwrap to raw arrays for the kernels
    def _raw_pages(p):
        if isinstance(p, QuantizedPages):
            return QuantizedPages(
                p.q._value if hasattr(p.q, "_value") else p.q,
                p.scale._value if hasattr(p.scale, "_value") else p.scale)
        return p

    def fn(qv, kv, vv, kp, vp, bt, sl):
        s = qv.shape[1]
        if s > 1 and chunked:
            if qv.shape[0] != 1:
                raise NotImplementedError(
                    "chunked paged prefill is per-request (B = 1); got "
                    f"batch {qv.shape[0]}")
            kp2, vp2 = write_paged_prompt_at(kp, vp, kv, vv, bt, sl)
            # query rows sit at absolute positions sl .. sl+s-1; rows
            # past the real prompt tail (final-chunk padding) emit
            # garbage the caller discards, and their K is masked off
            # every earlier row by causality. The pool is read through
            # the block table page by page — no gathered (B, T, Hkv, D)
            # view is ever materialized.
            attend = (paged_chunk_attention if use_pallas
                      else paged_chunk_attention_xla)
            out = attend(qv, kp2, vp2, bt, sl)
            sl2 = sl + s
        elif s > 1:
            # whole-prompt prefill contract: the sequences must be
            # EMPTY (chunked prefill rides PagedChunkState instead).
            # Enforce it whenever the lengths are concrete (eager
            # prototyping); under jit the docstring contract applies.
            if not isinstance(sl, jax.core.Tracer) and int(jnp.max(sl)):
                raise ValueError(
                    "paged prefill (S > 1) requires empty sequences "
                    f"(seq_lens all 0); got max {int(jnp.max(sl))}. "
                    "Use a PagedChunkState (chunked prefill) to extend "
                    "non-empty sequences, or decode one token at a "
                    "time after the prompt.")
            kp2, vp2 = write_paged_prompt(kp, vp, kv, vv, bt)
            # the prompt is the whole valid cache: causal self-attention
            out = cached_attention(qv, kv, vv, s)
            sl2 = sl + s
        else:
            kp2, vp2 = write_paged_kv(kp, vp, kv[:, 0], vv[:, 0], bt, sl)
            attend = paged_attention if use_pallas else paged_attention_xla
            out = attend(qv[:, 0], kp2, vp2, bt, sl + 1)[:, None]
            sl2 = sl + 1
        return out, kp2, vp2, sl2

    out, kp2, vp2, sl2 = apply_op(
        "paged_sdpa", fn, query, key, value,
        _raw_pages(state.k_pages), _raw_pages(state.v_pages),
        state.block_tables, state.seq_lens)
    return out, type(state)(kp2, vp2, state.block_tables, sl2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """SDPA with [batch, seq, heads, dim] layout (paddle convention —
    reference: python/paddle/nn/functional/flash_attention.py).
    Dispatches to the Pallas flash-attention kernel on TPU when enabled,
    through the per-shape FLAGS_flash_dispatch_table: benched-slower
    shape buckets resolve to the XLA dense path, benched-faster ones may
    carry their own block config."""
    from .. import flags
    # one snapshot covering the whole flash-dispatch decision (kernel
    # on/off, the min-seqlen gate, the per-shape table and its block
    # overrides) — resolved once per trace and threaded through
    # resolve_dispatch, never re-read per helper (tracecheck TRC001)
    snap = flags.snapshot(("use_pallas", "flash_attn_min_seqlen",
                           "flash_block_q", "flash_block_k",
                           "flash_compact_stats", "flash_dispatch_table"))
    if (snap.use_pallas and attn_mask is None and dropout_p == 0.0
            and flags.is_tpu_backend()
            and query.shape[1] >= snap.flash_attn_min_seqlen):
        try:
            from ..kernels.flash_attention import (flash_attention_bshd,
                                                   resolve_dispatch)
            kind, blk = resolve_dispatch(query.shape[1], snap)
        except ImportError:
            kind, blk = "dense", None
        if kind == "flash":
            bq, bk = blk if blk is not None else (None, None)
            try:
                return apply_op(
                    "flash_attention",
                    lambda q, k, v: flash_attention_bshd(
                        q, k, v, causal=is_causal, block_q=bq, block_k=bk,
                        snap=snap),
                    query, key, value)
            except NotImplementedError:
                pass

    mask_val = _val(attn_mask) if attn_mask is not None else None

    def fn(q, k, v):
        # GQA: unexpanded kv accepted everywhere; the dense path expands
        # here (the flash kernel above never does — Hkv bandwidth)
        if k.shape[2] != q.shape[2]:
            rep = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        # [B, S, H, D] -> [B, H, S, D]
        qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        scores = jnp.einsum("bhsd,bhtd->bhst", qt, kt) / math.sqrt(q.shape[-1])
        if is_causal:
            # iota comparison instead of a materialized tril constant: XLA
            # fuses it into the where; the pred[S,S] table showed up as the
            # TOP op (copy-start, 3% device time) in PROFILE_r05
            s, t = scores.shape[-2], scores.shape[-1]
            rows = jax.lax.broadcasted_iota(jnp.int32, (s, t), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (s, t), 1)
            scores = jnp.where(rows >= cols, scores, -1e30)
        if mask_val is not None:
            if mask_val.dtype == jnp.bool_:
                scores = jnp.where(mask_val, scores, -1e30)
            else:
                scores = scores + mask_val
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
        if dropout_p > 0.0 and training:
            keep = jax.random.bernoulli(next_key(), 1.0 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0).astype(q.dtype)
        out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
        return jnp.swapaxes(out, 1, 2)

    return apply_op("sdpa", fn, query, key, value)


# ---------------------------------------------------------------- conv/pool
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    elif isinstance(padding, int):
        pad = [(padding, padding), (padding, padding)]
    else:
        p = list(padding)
        if len(p) == 2 and all(isinstance(pi, (tuple, list)) for pi in p):
            pad = [tuple(p[0]), tuple(p[1])]  # already (lo, hi) pairs
        elif len(p) == 2:
            pad = [(p[0], p[0]), (p[1], p[1])]
        else:
            pad = [tuple(p[:2]), tuple(p[2:])]
    dn = jax.lax.conv_dimension_numbers(
        _val(x).shape, _val(weight).shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"))

    def fn(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
        if b:
            shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
            out = out + b[0].reshape(shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply_op("conv2d", fn, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    x2 = apply_op("unsq", lambda a: a[..., None, :] if data_format == "NCL" else a[:, None], x)
    w2 = apply_op("unsq", lambda a: a[..., None, :], weight)
    out = conv2d(x2, w2, bias,
                 stride=(1, stride if isinstance(stride, int) else stride[0]),
                 padding=((0, 0), (padding, padding)) if isinstance(padding, int) else padding,
                 dilation=(1, dilation if isinstance(dilation, int) else dilation[0]),
                 groups=groups, data_format="NCHW" if data_format == "NCL" else "NHWC")
    return apply_op("sq", lambda a: a.squeeze(-2 if data_format == "NCL" else 1), out)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, data_format="NCHW", output_size=None, name=None):
    """Gradient-of-conv formulation: lhs-dilated conv with the spatially
    flipped kernel; weight layout [in, out // groups, kh, kw] (the
    reference's conv2d_transpose convention).
    out = (L - 1) * stride - 2 * padding + dilation * (k - 1) + 1 + output_padding
    """
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    padding_ = (padding, padding) if isinstance(padding, int) else tuple(padding)
    op_ = ((output_padding, output_padding) if isinstance(output_padding, int)
           else tuple(output_padding))
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"conv2d_transpose: bad data_format {data_format}")
    if data_format == "NHWC":
        # transpose around the NCHW core: weights are layout-independent
        # ([in, out/g, kh, kw]) and XLA folds the transposes into the conv
        x_nchw = apply_op("nhwc_to_nchw", lambda a: a.transpose(0, 3, 1, 2), x)
        out = conv2d_transpose(
            x_nchw, weight, bias, stride=stride, padding=padding,
            output_padding=output_padding, dilation=dilation, groups=groups,
            data_format="NCHW", output_size=output_size)
        return apply_op("nchw_to_nhwc", lambda a: a.transpose(0, 2, 3, 1), out)
    kh, kw = _val(weight).shape[2], _val(weight).shape[3]
    pads = tuple(
        (dilation[i] * (k - 1) - padding_[i],
         dilation[i] * (k - 1) - padding_[i] + op_[i])
        for i, k in enumerate((kh, kw)))
    dn = ("NCHW", "IOHW", "NCHW")

    def fn(a, w, *b):
        wf = jnp.flip(w, (2, 3))
        if groups > 1:
            cin = wf.shape[0]
            # regroup [in, out/g, kh, kw] -> [in/g, out, kh, kw] group-major
            wf = wf.reshape(groups, cin // groups, *wf.shape[1:]) \
                .transpose(1, 0, 2, 3, 4) \
                .reshape(cin // groups, -1, *wf.shape[2:])
        out = jax.lax.conv_general_dilated(
            a, wf, window_strides=(1, 1), padding=pads,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if b:
            out = out + b[0].reshape(1, -1, 1, 1)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply_op("conv2d_transpose", fn, *args)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    # single source for pool padding/ceil semantics: functional_extra
    from .functional_extra import _max_pool_mask_nd, _pool_nd
    if return_mask:
        return _max_pool_mask_nd(x, 2, kernel_size,
                                 stride or kernel_size, padding,
                                 ceil_mode, "max_pool2d", data_format)
    fn, *_ = _pool_nd(_val(x), 2, kernel_size, stride or kernel_size,
                      padding, jax.lax.max, -jnp.inf, data_format, ceil_mode)
    return apply_op("max_pool2d", fn, x)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    from .functional_extra import _avg_pool_nd
    return _avg_pool_nd(x, 2, "avg_pool2d", kernel_size, stride, padding,
                        exclusive, ceil_mode, data_format, divisor_override)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    os = (output_size, output_size) if isinstance(output_size, int) else tuple(output_size)
    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a_ = a.reshape(n, c, os[0], h // os[0], os[1], w // os[1])
            return jnp.mean(a_, axis=(3, 5))
        n, h, w, c = a.shape
        a_ = a.reshape(n, os[0], h // os[0], os[1], w // os[1], c)
        return jnp.mean(a_, axis=(2, 4))
    return apply_op("adaptive_avg_pool2d", fn, x)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW", name=None):
    v = _val(x)
    if data_format == "NCHW":
        spatial = v.shape[2:]
    else:
        spatial = v.shape[1:-1]
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
        size = tuple(int(s * f) for s, f in zip(spatial, sf))
    size = tuple(int(_val(s)) for s in size)
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic",
              "trilinear": "linear", "linear": "linear", "area": "linear"}[mode]

    def fn(a):
        if data_format == "NCHW":
            tgt = a.shape[:2] + size
        else:
            tgt = (a.shape[0],) + size + (a.shape[-1],)
        return jax.image.resize(a, tgt, method=method)

    return apply_op("interpolate", fn, x)


upsample = interpolate


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = a.transpose(0, 1, 4, 2, 5, 3)
        return a.reshape(n, c // (r * r), h * r, w * r)
    return apply_op("pixel_shuffle", fn, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else tuple(kernel_sizes)
    st = (strides, strides) if isinstance(strides, int) else tuple(strides)
    pd = (paddings, paddings) if isinstance(paddings, int) else tuple(paddings)
    dl = (dilations, dilations) if isinstance(dilations, int) else tuple(dilations)
    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        cols = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                patch = a[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                          j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
                cols.append(patch)
        out = jnp.stack(cols, axis=2)  # [N, C, k*k, oh, ow]
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)
    return apply_op("unfold", fn, x)


# ---------------------------------------------------------------- sequence
def pad_sequence(sequences, padding_value=0.0, batch_first=False):
    vals = [_val(s) for s in sequences]
    maxlen = max(v.shape[0] for v in vals)
    padded = [jnp.pad(v, [(0, maxlen - v.shape[0])] + [(0, 0)] * (v.ndim - 1),
                      constant_values=padding_value) for v in vals]
    out = jnp.stack(padded, axis=0 if batch_first else 1)
    return Tensor(out)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l):
        n = l.shape[-1]
        if prior_dist is not None:
            return (1 - epsilon) * l + epsilon * _val(prior_dist)
        return (1 - epsilon) * l + epsilon / n
    return apply_op("label_smooth", fn, label)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([a[:, 1:, :fold], jnp.zeros_like(a[:, -1:, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(a[:, :1, fold:2 * fold]), a[:, :-1, fold:2 * fold]], axis=1)
        rest = a[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
    return apply_op("temporal_shift", fn, x)


# extended surface: 3-D conv/pool family, grid sampling, CTC, loss zoo
def softmax_(x, axis=-1, dtype=None, name=None):
    """In-place softmax (reference F.softmax_)."""
    out = softmax(x, axis=axis, dtype=dtype)
    x._value = out._value
    return x


from .functional_extra import *  # noqa: F401,F403,E402
