"""paddle_tpu.nn — module system + layer zoo (reference: python/paddle/nn/)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import Layer, LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from .layers.common import (  # noqa: F401
    GELU, GLU, ELU, CELU, SELU, PReLU, ReLU, ReLU6, SiLU, Swish, Mish,
    Sigmoid, Tanh, LeakyReLU, Hardswish, Hardsigmoid, Hardtanh,
    Softplus, Softshrink, Hardshrink, Tanhshrink, Softsign, LogSigmoid,
    Softmax, LogSoftmax,
    AdaptiveAvgPool2D, AvgPool2D, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    BCELoss, BCEWithLogitsLoss, Conv2D, Conv2DTranspose, CosineSimilarity,
    CrossEntropyLoss, Dropout, Dropout2D, Embedding, Flatten, GroupNorm,
    Identity, KLDivLoss, L1Loss, LayerNorm, Linear, MaxPool2D, MSELoss,
    NLLLoss, Pad2D, PixelShuffle, RMSNorm, SmoothL1Loss, Upsample,
    BatchNorm, SyncBatchNorm,
)
from .layers.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)
from .layers.rnn import (  # noqa: F401
    RNN, BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNNCellBase, SimpleRNN,
    SimpleRNNCell,
)
from .layers.extra import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool3D, AdaptiveMaxPool1D,
    AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D, AvgPool3D, Bilinear,
    ChannelShuffle, Conv1D, Conv1DTranspose, Conv3D, Conv3DTranspose,
    CosineEmbeddingLoss, CTCLoss, Fold, GaussianNLLLoss, HingeEmbeddingLoss,
    InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LocalResponseNorm,
    MarginRankingLoss, MaxPool1D, MaxPool3D, MultiLabelSoftMarginLoss,
    PairwiseDistance, PoissonNLLLoss, SoftMarginLoss, TripletMarginLoss,
    Unfold, ZeroPad2D, ZeroPad1D, ZeroPad3D, Unflatten, Softmax2D, Silu,
    FeatureAlphaDropout, TripletMarginWithDistanceLoss, HSigmoidLoss,
    AdaptiveLogSoftmaxWithLoss, FractionalMaxPool2D, FractionalMaxPool3D,
    AlphaDropout, Dropout3D, HuberLoss, MaxUnPool1D, MaxUnPool2D,
    MaxUnPool3D, Maxout, MultiMarginLoss, Pad1D, Pad3D, PixelUnshuffle,
    RNNTLoss, RReLU, SpectralNorm, ThresholdedReLU, UpsamplingBilinear2D,
    UpsamplingNearest2D,
)
from . import utils  # noqa: F401
from . import quant  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
