"""Layer: the module base class.

Reference: python/paddle/nn/layer/layers.py (``paddle.nn.Layer``).
Parameters/buffers/sublayers are held in ordered dicts with ``__setattr__``
routing; ``state_dict`` returns Tensors by dotted name. The functional
bridge for jit lives in paddle_tpu/jit (functional_call) — a Layer is also a
pytree of parameter values via ``raw_state`` for direct use with jax.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtype import to_jax_dtype
from ..core.place import get_default_dtype
from ..core.tensor import Parameter, Tensor
from . import initializer as I


class HookRemoveHelper:
    def __init__(self, container, key):
        self._container, self._key = container, key

    def remove(self):
        self._container.pop(self._key, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self.training = True
        self._dtype = dtype or get_default_dtype()
        self._name_scope = name_scope or type(self).__name__.lower()

    # ----------------------------------------------------------- attribute
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            self.__dict__.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            subs = self.__dict__.get("_sub_layers")
            if subs is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            self.__dict__.pop(name, None)
            subs[name] = value
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                    object.__setattr__(self, name, value)
                    return
                raise TypeError(f"cannot assign non-Parameter to parameter {name!r}")
            buffers = self.__dict__.get("_buffers")
            if buffers is not None and name in buffers:
                # reassigning a registered buffer must update the registry,
                # or state_dict would keep serving the stale tensor
                from ..core.tensor import Tensor as _T
                if value is None or isinstance(value, _T):
                    buffers[name] = value
                    return
                raise TypeError(f"cannot assign non-Tensor to buffer {name!r}")
            if self.__dict__.get("_sub_layers") is not None and name in self._sub_layers:
                del self._sub_layers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} has no attribute {name!r}")

    def __delattr__(self, name: str) -> None:
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ---------------------------------------------------------- construction
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias: bool = False,
        default_initializer: Optional[I.Initializer] = None,
    ) -> Parameter:
        from .param_attr import ParamAttr

        dtype = dtype or self._dtype
        init = default_initializer
        name = None
        lr = 1.0
        regularizer = None
        trainable = True
        if isinstance(attr, ParamAttr):
            init = attr.initializer or init
            name = attr.name
            lr = attr.learning_rate
            regularizer = attr.regularizer
            trainable = attr.trainable
        elif attr is False:
            raise ValueError("attr=False: caller should skip creating this parameter")
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        from ..framework.lazy import in_lazy_init
        lazy = in_lazy_init()
        if lazy:
            # meta tensor: shape+dtype only, zero bytes (paddle.LazyGuard)
            import jax
            from ..core.dtype import to_jax_dtype
            value = jax.ShapeDtypeStruct(
                tuple(int(s) for s in shape), to_jax_dtype(dtype))
        else:
            value = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(value, name=name, trainable=trainable)
        if lazy:
            # retain the initializer so framework.materialize / streaming
            # quantization can realize this parameter later without a
            # checkpoint (reference: lazy_init.py keeps the startup
            # program's init ops for the same reason)
            from ..framework.lazy import register_lazy
            register_lazy(p, init)
        p.optimize_attr["learning_rate"] = lr
        p.optimize_attr["regularizer"] = regularizer
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]) -> Optional[Parameter]:
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True) -> None:
        self.__dict__.pop(name, None)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)

    # ------------------------------------------------------------- traversal
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer, pfx in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{pfx}.{pname}" if pfx else pname), p

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer, pfx in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{pfx}.{bname}" if pfx else bname), b

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def _walk(self, prefix: str, include_sublayers: bool):
        yield "", self, prefix
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._walk(sub_prefix, True)

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        out = []
        for _, layer, _pfx in self._walk("", True):
            out.append(layer)
        return out if include_self else out[1:]

    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        for i, (name, layer, pfx) in enumerate(self._walk(prefix, True)):
            if i == 0 and not include_self:
                continue
            yield pfx, layer

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ------------------------------------------------------------ state dict
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True) -> Dict[str, Tensor]:
        out = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix,
                                             include_sublayers=include_sublayers):
            out[name] = p
        for name, layer, pfx in self._walk(structured_name_prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                out[f"{pfx}.{bname}" if pfx else bname] = b
        return out

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                v = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                if tuple(v.shape) != tuple(t.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: ckpt {tuple(v.shape)} vs model {tuple(t.shape)}")
                t._value = v.astype(jnp.result_type(t._value))
            else:
                missing.append(name)
        for k in state_dict:
            if k not in own:
                unexpected.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # ---------------------------------------------------------------- modes
    def train(self) -> "Layer":
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self) -> "Layer":
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ---------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ----------------------------------------------------------------- call
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # ------------------------------------------------------------- dtype/dev
    def to(self, device=None, dtype=None, blocking=None) -> "Layer":
        if dtype is not None:
            jd = to_jax_dtype(dtype)
            for p in self.parameters():
                if isinstance(p._value, jax.ShapeDtypeStruct):
                    # lazy (meta) param: retype the struct; the recorded
                    # initializer materializes in the new dtype later
                    if jnp.issubdtype(p._value.dtype, jnp.floating):
                        p._value = jax.ShapeDtypeStruct(p._value.shape, jd)
                    continue
                if jnp.issubdtype(jnp.result_type(p._value), jnp.floating):
                    p._value = p._value.astype(jd)
            for b in self.buffers():
                if b is not None and jnp.issubdtype(jnp.result_type(b._value), jnp.floating):
                    b._value = b._value.astype(jd)
        if device is not None:
            devs = jax.devices("cpu") if str(device).startswith("cpu") else jax.devices()
            for t in list(self.parameters()) + list(self.buffers()):
                if t is not None and isinstance(t._value, jax.Array):
                    t._value = jax.device_put(t._value, devs[0])
        return self

    def astype(self, dtype) -> "Layer":
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def full_name(self) -> str:
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self) -> str:
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    # ------------------------------------------------------- functional view
    def raw_state(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """(params, buffers) as flat name->jax.Array dicts — the pytree the
        jitted train step closes over."""
        params = {k: p._value for k, p in self.named_parameters() if not p.stop_gradient}
        frozen = {k: p._value for k, p in self.named_parameters() if p.stop_gradient}
        buffers = {k: (b._value if b is not None else None) for k, b in self.named_buffers()}
        buffers.update(frozen)
        return params, buffers

    def load_raw_state(self, params: Dict[str, Any], buffers: Optional[Dict[str, Any]] = None):
        named = dict(self.named_parameters())
        for k, v in params.items():
            if k in named:
                named[k]._value = v
        if buffers:
            named_b = dict(self.named_buffers())
            for k, v in buffers.items():
                if k in named_b and v is not None and named_b[k] is not None:
                    named_b[k]._value = v


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and (
            layers[0] and isinstance(layers[0][0], (list, tuple))
        ):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, l in enumerate(sublayers or []):
            self.add_sublayer(str(i), l)

    def append(self, sublayer: Layer) -> "LayerList":
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def extend(self, sublayers) -> "LayerList":
        for l in sublayers:
            self.append(l)
        return self

    def insert(self, index: int, sublayer: Layer) -> None:
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def append(self, parameter: Parameter) -> "ParameterList":
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def update(self, sublayers) -> None:
        items = sublayers.items() if isinstance(sublayers, dict) else sublayers
        for k, v in items:
            self.add_sublayer(k, v)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()
