"""Functional surface extensions: 3-D conv/pool family, grid sampling, CTC
loss, and the margin/embedding loss zoo
(reference: python/paddle/nn/functional/{conv,pooling,vision,loss}.py).

CTC is the one nontrivial kernel here: the reference binds warp-ctc
(paddle/fluid/operators/warpctc_op.*); the TPU-native version is a
log-semiring forward DP as one ``lax.scan`` over time, vmapped over the
batch — static shapes, masked tails for variable input/label lengths.
"""

from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, _val

_NEG_INF = -1e30


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


# ------------------------------------------------------------------ conv3d
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    """(reference: python/paddle/nn/functional/conv.py::conv3d)."""
    stride, dilation = _triple(stride), _triple(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    elif isinstance(padding, int):
        pad = [(padding,) * 2] * 3
    else:
        p = list(padding)
        pad = [(pi, pi) for pi in p] if len(p) == 3 else \
            [tuple(p[0:2]), tuple(p[2:4]), tuple(p[4:6])]
    dn = jax.lax.conv_dimension_numbers(
        _val(x).shape, _val(weight).shape,
        ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW"
        else ("NDHWC", "OIDHW", "NDHWC"))

    def fn(a, w, b):
        out = jax.lax.conv_general_dilated(
            a, w, stride, pad, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if b is not None:
            shape = ((1, -1, 1, 1, 1) if data_format == "NCDHW"
                     else (1, 1, 1, 1, -1))
            out = out + b.reshape(shape)
        return out

    return apply_op("conv3d", fn, x, weight, bias)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCL", name=None):
    from .functional import conv2d_transpose

    st = stride if isinstance(stride, int) else stride[0]
    pd = padding if isinstance(padding, int) else padding[0]
    dl = dilation if isinstance(dilation, int) else dilation[0]
    op = output_padding if isinstance(output_padding, int) \
        else output_padding[0]
    x2 = apply_op("unsq", lambda a: a[..., None, :], x)
    w2 = apply_op("unsq", lambda a: a[..., None, :], weight)
    out = conv2d_transpose(x2, w2, bias, stride=(1, st), padding=(0, pd),
                           output_padding=(0, op), groups=groups,
                           dilation=(1, dl), data_format="NCHW")
    return apply_op("sq", lambda a: a.squeeze(-2), out)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCDHW", name=None):
    """Gradient-of-conv3d formulation (reference conv3d_transpose)."""
    stride, dilation = _triple(stride), _triple(dilation)
    padding = _triple(padding) if isinstance(padding, int) else tuple(padding)
    output_padding = _triple(output_padding) \
        if isinstance(output_padding, int) else tuple(output_padding)
    dn = jax.lax.conv_dimension_numbers(
        _val(x).shape, _val(weight).shape,
        ("NCDHW", "IODHW", "NCDHW"))
    # transpose conv == lhs-dilated conv with flipped kernel padding
    pads = tuple(
        (dilation[i] * (_val(weight).shape[2 + i] - 1) - padding[i],
         dilation[i] * (_val(weight).shape[2 + i] - 1) - padding[i]
         + output_padding[i])
        for i in range(3))

    def fn(a, w, b):
        out = jax.lax.conv_general_dilated(
            a, jnp.flip(w, (2, 3, 4)), (1, 1, 1), pads,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1, 1)
        return out

    return apply_op("conv3d_transpose", fn, x, weight, bias)


# ------------------------------------------------------------------- pools
def _ceil_extra(L, k, s, p, ceil_mode):
    """Extra right-padding so reduce_window emits ceil((L+2p-k)/s)+1
    positions instead of floor (paddle ceil_mode=True semantics). A window
    that would START in the right padding is dropped (torch/paddle clamp) —
    without it the final position is all-padding: -inf for max pool, 0/0
    for exclusive avg."""
    if not ceil_mode:
        return 0
    out_ceil = -(-(L + 2 * p - k) // s) + 1
    if (out_ceil - 1) * s >= L + p:
        out_ceil -= 1
    return max(0, (out_ceil - 1) * s + k - (L + 2 * p))


def _pool_nd(x, nd, kernel, stride, padding, reducer, init, fmt,
             ceil_mode=False):
    kernel = (kernel,) * nd if isinstance(kernel, int) else tuple(kernel)
    stride = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    padding = (padding,) * nd if isinstance(padding, int) else tuple(padding)
    channels_last = fmt.endswith("C")
    spatial = x.shape[-nd - 1:-1] if channels_last else x.shape[-nd:]
    sp = tuple((p, p + _ceil_extra(L, k, s, p, ceil_mode))
               for L, k, s, p in zip(spatial, kernel, stride, padding))
    if channels_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = ((0, 0),) + sp + ((0, 0),)
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = ((0, 0), (0, 0)) + sp

    def fn(a):
        return jax.lax.reduce_window(a, init, reducer, window, strides, pads)

    return fn, window, strides, pads


def _avg_pool_nd(x, nd, op_name, kernel_size, stride, padding, exclusive,
                 ceil_mode, data_format, divisor_override=None):
    """exclusive=True (reference default) divides each window by the count
    of REAL elements in it — padding (incl. ceil_mode extra) never enters
    the denominator. exclusive=False divides by the full kernel size."""
    fn, window, strides, pads = _pool_nd(
        x, nd, kernel_size, stride or kernel_size, padding,
        jax.lax.add, 0.0, data_format, ceil_mode)

    def avg(a):
        s = fn(a)
        if divisor_override:
            return s / divisor_override
        if exclusive:
            cnt = jax.lax.reduce_window(jnp.ones_like(a), 0.0, jax.lax.add,
                                        window, strides, pads)
            return s / cnt
        return s / float(np.prod(window))

    return apply_op(op_name, avg, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        return _max_pool_mask_nd(x, 1, kernel_size,
                                 stride or kernel_size, padding,
                                 ceil_mode, "max_pool1d", data_format)
    fn, *_ = _pool_nd(x, 1, kernel_size, stride or kernel_size, padding,
                      jax.lax.max, -jnp.inf, data_format, ceil_mode)
    return apply_op("max_pool1d", fn, x)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _avg_pool_nd(x, 1, "avg_pool1d", kernel_size, stride, padding,
                        exclusive, ceil_mode, data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_mask_nd(x, 3, kernel_size,
                                 stride or kernel_size, padding,
                                 ceil_mode, "max_pool3d", data_format)
    fn, *_ = _pool_nd(x, 3, kernel_size, stride or kernel_size, padding,
                      jax.lax.max, -jnp.inf, data_format, ceil_mode)
    return apply_op("max_pool3d", fn, x)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCDHW", name=None):
    return _avg_pool_nd(x, 3, "avg_pool3d", kernel_size, stride, padding,
                        exclusive, ceil_mode, data_format)


def adaptive_avg_pool1d(x, output_size, name=None):
    return apply_op("adaptive_avg_pool1d",
                    lambda a: _adaptive_reduce(a, (output_size,), jnp.mean),
                    x)


def _adaptive_reduce(a, out_sizes, reduce_fn):
    """Adaptive pooling over the trailing len(out_sizes) spatial dims via
    per-window slicing (paddle's start/end index formula)."""
    nd = len(out_sizes)
    spatial = a.shape[-nd:]

    def pool_axis(arr, axis, in_size, out_size):
        pieces = []
        for i in range(out_size):
            s = (i * in_size) // out_size
            e = -(-((i + 1) * in_size) // out_size)
            sl = [slice(None)] * arr.ndim
            sl[axis] = slice(s, e)
            pieces.append(reduce_fn(arr[tuple(sl)], axis=axis,
                                    keepdims=True))
        return jnp.concatenate(pieces, axis=axis)

    for d in range(nd):
        axis = a.ndim - nd + d
        a = pool_axis(a, axis, spatial[d], out_sizes[d])
    return a


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError("adaptive_max_pool1d(return_mask=True)")
    return apply_op("adaptive_max_pool1d",
                    lambda a: _adaptive_reduce(a, (output_size,), jnp.max), x)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError("adaptive_max_pool2d(return_mask=True)")
    out = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    return apply_op("adaptive_max_pool2d",
                    lambda a: _adaptive_reduce(a, out, jnp.max), x)


def adaptive_avg_pool3d(x, output_size, name=None):
    out = _triple(output_size)
    return apply_op("adaptive_avg_pool3d",
                    lambda a: _adaptive_reduce(a, out, jnp.mean), x)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError("adaptive_max_pool3d(return_mask=True)")
    out = _triple(output_size)
    return apply_op("adaptive_max_pool3d",
                    lambda a: _adaptive_reduce(a, out, jnp.max), x)


# ---------------------------------------------------------- grid sampling
def affine_grid(theta, out_shape, align_corners=True, name=None):
    """(reference: python/paddle/nn/functional/vision.py::affine_grid).
    ``theta``: (N, 2, 3); ``out_shape``: [N, C, H, W] -> grid (N, H, W, 2)."""
    n, _, h, w = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)     # (H, W, 3)
        return jnp.einsum("hwk,njk->nhwj", base, th)          # (N, H, W, 2)

    return apply_op("affine_grid", fn, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """(reference: python/paddle/nn/functional/vision.py::grid_sample).
    x: (N, C, H, W); grid: (N, Hg, Wg, 2) in [-1, 1] (x, y) order."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"mode must be bilinear|nearest, got {mode!r}")
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(
            f"padding_mode={padding_mode!r}; zeros|border supported")

    def fn(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def gather(ix, iy):
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            vals = a[jnp.arange(n)[:, None, None], :, iyc, ixc]  # (N,Hg,Wg,C)
            if padding_mode == "zeros":
                ok = ((ix >= 0) & (ix <= w - 1)
                      & (iy >= 0) & (iy <= h - 1))
                vals = jnp.where(ok[..., None], vals, 0.0)
            return vals

        if mode == "nearest":
            out = gather(jnp.round(fx).astype(jnp.int32),
                         jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            wx = (fx - x0)[..., None]
            wy = (fy - y0)[..., None]
            out = (gather(x0, y0) * (1 - wx) * (1 - wy)
                   + gather(x0 + 1, y0) * wx * (1 - wy)
                   + gather(x0, y0 + 1) * (1 - wx) * wy
                   + gather(x0 + 1, y0 + 1) * wx * wy)
        return jnp.moveaxis(out, -1, 1)                       # (N, C, Hg, Wg)

    return apply_op("grid_sample", fn, x, grid)


# -------------------------------------------------------------------- CTC
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC (reference: python/paddle/nn/functional/loss.py::ctc_loss over
    the warp-ctc op). Follows the reference convention: ``log_probs`` are
    unnormalized logits of shape (T, B, C) — log_softmax is applied
    internally (warp-ctc semantics); labels (B, L) padded; lengths (B,)."""

    def fn(logits, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)   # (T, B, C)
        lp = jnp.moveaxis(lp, 1, 0)                               # (B, T, C)

        def one(lp_b, lab_b, T_b, L_b):
            T, C = lp_b.shape
            L = lab_b.shape[0]
            S = 2 * L + 1
            ext = jnp.full((S,), blank, lab_b.dtype)
            ext = ext.at[1::2].set(lab_b)
            # skip transition allowed where ext[s] != blank and != ext[s-2]
            prev2 = jnp.concatenate([jnp.full((2,), -1, ext.dtype),
                                     ext[:-2]])
            can_skip = (ext != blank) & (ext != prev2)

            alpha0 = jnp.full((S,), _NEG_INF)
            alpha0 = alpha0.at[0].set(lp_b[0, blank])
            alpha0 = alpha0.at[1].set(
                jnp.where(L_b > 0, lp_b[0, ext[1]], _NEG_INF))

            def step(alpha, t):
                shift1 = jnp.concatenate([jnp.full((1,), _NEG_INF),
                                          alpha[:-1]])
                shift2 = jnp.concatenate([jnp.full((2,), _NEG_INF),
                                          alpha[:-2]])
                shift2 = jnp.where(can_skip, shift2, _NEG_INF)
                merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
                new = merged + lp_b[t, ext]
                # freeze past this sequence's end
                alpha = jnp.where(t < T_b, new, alpha)
                return alpha, None

            alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
            idx_last = jnp.clip(2 * L_b, 0, S - 1)
            idx_prev = jnp.clip(2 * L_b - 1, 0, S - 1)
            total = jnp.logaddexp(alpha[idx_last],
                                  jnp.where(L_b > 0, alpha[idx_prev],
                                            _NEG_INF))
            return -total

        losses = jax.vmap(one)(lp, lab, in_len, lab_len)          # (B,)
        if norm_by_times:
            losses = losses / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        if reduction == "mean":
            # reference divides by label length before averaging
            return jnp.mean(
                losses / jnp.maximum(lab_len.astype(jnp.float32), 1.0))
        if reduction == "sum":
            return jnp.sum(losses)
        return losses

    return apply_op("ctc_loss", fn, log_probs, labels, input_lengths,
                    label_lengths)


# ------------------------------------------------------------ loss family
def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return apply_op(
        "margin_ranking_loss",
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin),
                                reduction),
        input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    return apply_op(
        "hinge_embedding_loss",
        lambda a, y: _reduce(jnp.where(y == 1.0, a,
                                       jnp.maximum(0.0, margin - a)),
                             reduction),
        input, label)


def soft_margin_loss(input, label, reduction="mean", name=None):
    return apply_op(
        "soft_margin_loss",
        lambda a, y: _reduce(jnp.log1p(jnp.exp(-y * a)), reduction),
        input, label)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    def fn(a, y, w):
        per = -(y * jax.nn.log_sigmoid(a)
                + (1 - y) * jax.nn.log_sigmoid(-a))
        if w is not None:
            per = per * w
        return _reduce(jnp.mean(per, -1), reduction)

    return apply_op("multi_label_soft_margin_loss", fn, input, label, weight)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        per = jnp.where(y == 1.0, 1.0 - cos,
                        jnp.maximum(0.0, cos - margin))
        return _reduce(per, reduction)

    return apply_op("cosine_embedding_loss", fn, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def fn(a, pos, neg):
        def dist(u, v):
            return jnp.power(
                jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p), -1), 1 / p)

        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_an = jnp.minimum(d_an, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, d_ap - d_an + margin), reduction)

    return apply_op("triplet_margin_loss", fn, input, positive, negative)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    return apply_op(
        "pairwise_distance",
        lambda a, b: jnp.power(
            jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), -1,
                    keepdims=keepdim), 1 / p),
        x, y)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(a, y):
        if log_input:
            per = jnp.exp(a) - y * a
        else:
            per = a - y * jnp.log(a + epsilon)
        if full:
            stirling = (y * jnp.log(y) - y
                        + 0.5 * jnp.log(2 * math.pi * y))
            per = per + jnp.where(y > 1, stirling, 0.0)
        return _reduce(per, reduction)

    return apply_op("poisson_nll_loss", fn, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        per = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            per = per + 0.5 * math.log(2 * math.pi)
        return _reduce(per, reduction)

    return apply_op("gaussian_nll_loss", fn, input, label, variance)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(lg, y, norm):
        p = jax.nn.sigmoid(lg)
        ce = -(y * jax.nn.log_sigmoid(lg)
               + (1 - y) * jax.nn.log_sigmoid(-lg))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        per = a_t * jnp.power(1 - p_t, gamma) * ce
        if norm is not None:
            per = per / norm
        return _reduce(per, reduction)

    return apply_op("sigmoid_focal_loss", fn, logit, label, normalizer)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """(reference: python/paddle/nn/functional/loss.py::dice_loss);
    input (N, ..., C) probabilities, label (N, ..., 1) int class ids."""

    def fn(a, y):
        c = a.shape[-1]
        oh = jax.nn.one_hot(y[..., 0], c, dtype=a.dtype)
        flat_a = a.reshape(a.shape[0], -1)
        flat_y = oh.reshape(a.shape[0], -1)
        inter = jnp.sum(flat_a * flat_y, -1)
        union = jnp.sum(flat_a, -1) + jnp.sum(flat_y, -1)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply_op("dice_loss", fn, input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op(
        "log_loss",
        lambda a, y: -(y * jnp.log(a + epsilon)
                       + (1 - y) * jnp.log(1 - a + epsilon)),
        input, label)


def square_error_cost(input, label, name=None):
    return apply_op("square_error_cost", lambda a, y: (a - y) ** 2,
                    input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """(reference: python/paddle/nn/functional/loss.py::npair_loss)."""

    def fn(a, p, y):
        y = y.reshape(-1, 1)
        same = (y == y.T).astype(a.dtype)
        same = same / jnp.sum(same, -1, keepdims=True)
        sim = a @ p.T
        xent = jnp.mean(
            jnp.sum(-same * jax.nn.log_softmax(sim, -1), -1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, -1))
                        + jnp.mean(jnp.sum(p * p, -1))) / 2
        return xent + reg

    return apply_op("npair_loss", fn, anchor, positive, labels)


# ------------------------------------------------------------------- misc
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def fn(a):
        sq = a * a
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        pad = [(0, 0)] * a.ndim
        pad[ch_axis] = (size // 2, (size - 1) // 2)
        window = [1] * a.ndim
        window[ch_axis] = size
        s = jax.lax.reduce_window(jnp.pad(sq, pad), 0.0, jax.lax.add,
                                  tuple(window), (1,) * a.ndim,
                                  [(0, 0)] * a.ndim)
        return a / jnp.power(k + alpha * s / size, beta)

    return apply_op("local_response_norm", fn, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, groups, c // groups, h, w) \
                .swapaxes(1, 2).reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, groups, c // groups) \
            .swapaxes(3, 4).reshape(n, h, w, c)

    return apply_op("channel_shuffle", fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (reference: python/paddle/nn/functional/common.py::fold);
    x: (N, C*kh*kw, L) -> (N, C, H, W). Scatter-add of unfold patches."""
    oh, ow = ((output_sizes, output_sizes)
              if isinstance(output_sizes, int) else tuple(output_sizes))
    kh, kw = ((kernel_sizes, kernel_sizes)
              if isinstance(kernel_sizes, int) else tuple(kernel_sizes))
    sh, sw = (strides, strides) if isinstance(strides, int) \
        else tuple(strides)
    ph, pw = (paddings, paddings) if isinstance(paddings, int) \
        else tuple(paddings)
    dh, dw = (dilations, dilations) if isinstance(dilations, int) \
        else tuple(dilations)

    def fn(a):
        n, ckk, L = a.shape
        c = ckk // (kh * kw)
        nh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        nw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        a = a.reshape(n, c, kh, kw, nh, nw)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                patch = a[:, :, i, j]                       # (n, c, nh, nw)
                out = jax.lax.dynamic_update_slice(
                    out,
                    jax.lax.dynamic_slice(
                        out, (0, 0, i * dh, j * dw),
                        (n, c, (nh - 1) * sh + 1, (nw - 1) * sw + 1))
                    .at[:, :, ::sh, ::sw].add(patch),
                    (0, 0, i * dh, j * dw))
        return out[:, :, ph:ph + oh, pw:pw + ow]

    return apply_op("fold", fn, x)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    p = (padding,) * 4 if isinstance(padding, int) else tuple(padding)

    def fn(a):
        if data_format == "NCHW":
            return jnp.pad(a, ((0, 0), (0, 0), (p[2], p[3]), (p[0], p[1])))
        return jnp.pad(a, ((0, 0), (p[2], p[3]), (p[0], p[1]), (0, 0)))

    return apply_op("zeropad2d", fn, x)


# ----------------------------------------------- coverage-manifest additions
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """nn.functional.pad — same op as paddle.pad (reference exposes both)."""
    from ..ops.manipulation import pad as _pad
    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    """reference: python/paddle/nn/functional/loss.py huber_loss."""
    def fn(a, b):
        d = a - b
        ad = jnp.abs(d)
        out = jnp.where(ad <= delta, 0.5 * d * d,
                        delta * (ad - 0.5 * delta))
        if reduction == "mean":
            return out.mean()
        if reduction == "sum":
            return out.sum()
        return out
    return apply_op("huber_loss", fn, input, label)


def maxout(x, groups, axis=1, name=None):
    """reference: nn/functional/activation.py maxout — max over channel
    groups: C -> C/groups."""
    def fn(a):
        ax = axis if axis >= 0 else a.ndim + axis
        c = a.shape[ax]
        if c % groups:
            raise ValueError(f"channels {c} not divisible by groups {groups}")
        shp = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return a.reshape(shp).max(axis=ax + 1)
    return apply_op("maxout", fn, x)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """reference: loss.py multi_margin_loss (multi-class hinge)."""
    def fn(a, lab, *w):
        n, c = a.shape
        correct = jnp.take_along_axis(a, lab[:, None], axis=1)
        m = jnp.maximum(0.0, margin - correct + a) ** p
        if w:
            m = m * w[0][lab][:, None]
        mask = jax.nn.one_hot(lab, c, dtype=a.dtype)
        out = (m * (1 - mask)).sum(axis=1) / c
        if reduction == "mean":
            return out.mean()
        if reduction == "sum":
            return out.sum()
        return out
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply_op("multi_margin_loss", fn, *args)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    """reference: vision.py pixel_unshuffle — inverse of pixel_shuffle."""
    r = downscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            return a.transpose(0, 1, 3, 5, 2, 4).reshape(
                n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        return a.transpose(0, 1, 3, 5, 2, 4).reshape(
            n, h // r, w // r, c * r * r)
    return apply_op("pixel_unshuffle", fn, x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    """reference: activation.py rrelu — randomized leaky slope in train,
    mean slope in eval."""
    if not training:
        slope = (lower + upper) / 2.0
        return apply_op("rrelu", lambda a: jnp.where(a >= 0, a, a * slope), x)
    from ..framework.random import next_key

    key = next_key()

    def fn(a):
        slopes = jax.random.uniform(key, a.shape, jnp.float32,
                                    lower, upper).astype(a.dtype)
        return jnp.where(a >= 0, a, a * slopes)
    return apply_op("rrelu", fn, x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op("thresholded_relu",
                    lambda a: jnp.where(a > threshold, a, value), x)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """reference: nn/functional/extension.py sequence_mask:
    out[..., j] = j < x[...]."""
    from ..core.dtype import to_jax_dtype

    def fn(lens):
        m = maxlen if maxlen is not None else int(jnp.max(lens))
        iota = jnp.arange(m)
        return (iota < lens[..., None]).astype(to_jax_dtype(dtype))
    if maxlen is None:
        import numpy as _np
        lens = _val(x)
        m = int(_np.asarray(lens).max())
        return apply_op("sequence_mask",
                        lambda l: (jnp.arange(m) < l[..., None]).astype(
                            to_jax_dtype(dtype)), x)
    return apply_op("sequence_mask", fn, x)


# ------------------------------------------- max pool with indices + unpool
def _max_pool_mask_nd(x, nd, kernel, stride, padding, ceil_mode, op_name,
                      data_format="NCX"):
    """return_mask=True path: manual -inf padding + patch extraction +
    argmax. Indices are flat positions in the UNPADDED per-channel spatial
    map (the reference convention, feeding max_unpool). Channels-last
    formats transpose around the NC* core (the spatial flat index is
    layout-independent)."""
    if data_format.endswith("C") and len(data_format) > 2:
        perm_in = (0, len(data_format) - 1) + tuple(
            range(1, len(data_format) - 1))
        from ..ops.manipulation import transpose as _tp
        vals, idx = _max_pool_mask_nd(
            _tp(x, list(perm_in)), nd, kernel, stride, padding, ceil_mode,
            op_name)
        perm_out = (0,) + tuple(range(2, nd + 2)) + (1,)
        return _tp(vals, list(perm_out)), _tp(idx, list(perm_out))
    kernel = (kernel,) * nd if isinstance(kernel, int) else tuple(kernel)
    stride = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    padding = (padding,) * nd if isinstance(padding, int) else tuple(padding)

    def fn(a):
        spatial = a.shape[2:]
        sp = tuple((p, p + _ceil_extra(L, k, s, p, ceil_mode))
                   for L, k, s, p in zip(spatial, kernel, stride, padding))
        ap = jnp.pad(a, ((0, 0), (0, 0)) + sp, constant_values=_NEG_INF)
        patches = jax.lax.conv_general_dilated_patches(
            ap, kernel, stride, [(0, 0)] * nd)
        n, ck, *out_sp = patches.shape
        c = a.shape[1]
        # patch channel layout: (C, *kernel) row-major
        patches = patches.reshape((n, c, int(np.prod(kernel))) + tuple(out_sp))
        vals = patches.max(axis=2)
        loc = patches.argmax(axis=2)                       # local kernel idx
        # local kernel index -> absolute (unpadded) flat spatial index
        rem = loc
        idx = jnp.zeros_like(loc)
        for d in range(nd - 1, -1, -1):
            kd = rem % kernel[d]
            rem = rem // kernel[d]
            out_idx = jax.lax.broadcasted_iota(loc.dtype, loc.shape, 2 + d)
            abs_d = out_idx * stride[d] - padding[d] + kd
            m = 1
            for dd in range(d + 1, nd):
                m *= spatial[dd]
            idx = idx + abs_d * m
        return vals, idx.astype(jnp.int32)

    # through apply_op so gradients flow into the pooled values (the
    # int index output gets a float0 cotangent and stays grad-free)
    return apply_op(op_name, fn, x)


def _max_unpool_nd(x, indices, nd, kernel, stride, padding, output_size,
                   op_name):
    kernel = (kernel,) * nd if isinstance(kernel, int) else tuple(kernel)
    stride_ = stride or kernel
    stride_ = ((stride_,) * nd if isinstance(stride_, int)
               else tuple(stride_))
    padding = (padding,) * nd if isinstance(padding, int) else tuple(padding)

    def fn(a, idx):
        n, c, *out_sp = a.shape
        if output_size is not None:
            target = tuple(output_size[-nd:])
        else:
            target = tuple((o - 1) * s - 2 * p + k for o, s, p, k in
                           zip(out_sp, stride_, padding, kernel))
        flat_sz = int(np.prod(target))
        af = a.reshape(n * c, -1)
        ix = idx.reshape(n * c, -1)

        def scatter_one(vals, ii):
            return jnp.zeros((flat_sz,), a.dtype).at[ii].set(vals)

        out = jax.vmap(scatter_one)(af, ix)
        return out.reshape((n, c) + target)

    return apply_op(op_name, fn, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """reference: nn/functional/pooling.py max_unpool1d."""
    return _max_unpool_nd(x, indices, 1, kernel_size, stride, padding,
                          output_size, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool_nd(x, indices, 2, kernel_size, stride, padding,
                          output_size, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool_nd(x, indices, 3, kernel_size, stride, padding,
                          output_size, "max_unpool3d")


def rnnt_loss(logits, labels, logit_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-Transducer loss (reference: python/paddle/nn/functional/loss.py
    rnnt_loss over warprnnt). TPU-native: log-semiring forward DP
    alpha[t, u] = logaddexp(alpha[t-1, u] + blank(t-1, u),
                            alpha[t, u-1] + label(t, u-1))
    as a lax.scan over T with an inner scan over U, vmapped over the
    batch. Static (T, U) grid, variable lengths via masked gather."""
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: FastEmit regularization is not implemented; pass "
            "fastemit_lambda=0")
    def fn(lg, lab, tl, ul):
        b, t_max, u1, v = lg.shape
        u_max = u1 - 1
        lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        blank_lp = lp[..., blank]                          # (B, T, U+1)
        lab_idx = jnp.minimum(lab, v - 1)
        y_lp = jnp.take_along_axis(
            lp[:, :, :u_max, :], lab_idx[:, None, :, None],
            axis=-1)[..., 0]                               # (B, T, U)
        # mask label positions beyond each sample's label length
        u_iota = jnp.arange(u_max)[None, None, :]
        y_lp = jnp.where(u_iota < ul[:, None, None], y_lp, _NEG_INF)

        def one(blank_b, y_b, tl_b, ul_b):
            # alpha row for t=0: alpha[0, u] = sum of label steps
            first = jnp.concatenate(
                [jnp.zeros((1,)), jnp.cumsum(y_b[0])])     # (U+1,)

            def t_step(prev, xs):
                blank_t_1, y_t = xs                        # rows t-1, t
                base = prev + blank_t_1                    # vertical move

                def u_step(carry, bu):
                    b_u, y_u_1 = bu
                    val = jnp.logaddexp(b_u, carry + y_u_1)
                    return val, val

                first_v = base[0]
                _, rest = jax.lax.scan(
                    u_step, first_v,
                    (base[1:], y_t))
                row = jnp.concatenate([first_v[None], rest])
                return row, None

            def t_step_collect(prev, xs):
                row, _ = t_step(prev, xs)
                return row, row

            _, rows = jax.lax.scan(t_step_collect, first,
                                   (blank_b[:-1], y_b[1:]))
            all_rows = jnp.concatenate([first[None], rows], axis=0)
            final_row = all_rows[jnp.maximum(tl_b - 1, 0)]
            final_alpha = final_row[ul_b]
            final_blank = blank_b[jnp.maximum(tl_b - 1, 0), ul_b]
            return -(final_alpha + final_blank)

        losses = jax.vmap(one)(blank_lp, y_lp, tl, ul)
        if reduction == "mean":
            return losses.mean()
        if reduction == "sum":
            return losses.sum()
        return losses

    return apply_op("rnnt_loss", fn, logits, labels, logit_lengths,
                    label_lengths)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """reference: F.feature_alpha_dropout — alpha dropout over whole
    channel maps (mask shape (N, C, 1, ...))."""
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(_val(x))
    from ..framework.random import next_key
    import jax as _jax

    def fn(a):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        mask_shape = a.shape[:2] + (1,) * (a.ndim - 2)
        keep = _jax.random.bernoulli(next_key(), 1.0 - p, mask_shape)
        am = 1.0 / jnp.sqrt((alpha_p ** 2 * p + 1.0) * (1.0 - p))
        bm = -am * alpha_p * p
        out = jnp.where(keep, a, alpha_p)
        return out * am + bm
    return apply_op("feature_alpha_dropout", fn, x)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """reference: F.triplet_margin_with_distance_loss — triplet loss
    with a user distance callable."""
    dist = distance_function or (
        lambda a, b: ((a - b) ** 2).sum(-1).sqrt())
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        from ..ops import math as _m
        d_neg = _m.minimum(d_neg, dist(positive, negative))
    from ..ops import math as _m
    loss = _m.maximum(d_pos - d_neg + margin,
                      Tensor(jnp.zeros((), _val(d_pos).dtype)))
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """reference: F.hsigmoid_loss — hierarchical sigmoid over a complete
    binary tree (default tree when no custom path is given)."""
    def fn(x, lab, w, *rest):
        b = rest[0] if rest else None
        n = x.shape[0]
        code_len = int(np.ceil(np.log2(max(2, num_classes))))
        # complete-binary-tree paths: internal node ids + left/right codes
        labels = lab.reshape(-1)
        nodes = []
        codes = []
        cur = labels + num_classes          # leaf position in heap order
        for _ in range(code_len):
            parent = cur // 2
            nodes.append(parent - 1)        # internal nodes are 1-based
            codes.append((cur % 2).astype(x.dtype))
            cur = parent
        node_idx = jnp.stack(nodes, 1)      # (N, L)
        code = jnp.stack(codes, 1)          # (N, L): 1 = right child
        valid = node_idx < (num_classes - 1)
        node_idx = jnp.clip(node_idx, 0, w.shape[0] - 1)
        wn = w[node_idx]                    # (N, L, D)
        logits = jnp.einsum("nld,nd->nl", wn, x)
        if b is not None:
            logits = logits + b.reshape(-1)[node_idx]
        # p(right) = sigmoid(logit); loss = -sum log p(code)
        logp = -jnp.logaddexp(0.0, jnp.where(code > 0, -logits, logits))
        loss = -(jnp.where(valid, logp, 0.0)).sum(1)
        return loss[:, None]
    args = [input, label, weight] + ([bias] if bias is not None else [])
    return apply_op("hsigmoid_loss", fn, *args)


def class_center_sample(label, num_classes, num_samples, group=None):
    """reference: F.class_center_sample (PartialFC sampling): returns
    (remapped_label, sampled_class_indices). Positive classes always
    kept; negatives fill up to num_samples (deterministic fill — jax
    RNG sampling of the remainder)."""
    from ..framework.random import next_key
    import jax as _jax
    lab = _val(label).reshape(-1)
    pos = np.unique(np.asarray(lab))
    n_extra = max(0, num_samples - pos.size)
    rest = np.setdiff1d(np.arange(num_classes), pos)
    if n_extra and rest.size:
        perm = np.asarray(_jax.random.permutation(next_key(), rest.size))
        extra = rest[perm[:n_extra]]
    else:
        extra = rest[:0]
    sampled = np.sort(np.concatenate([pos, extra]))
    remap = np.full((num_classes,), -1, np.int64)
    remap[sampled] = np.arange(sampled.size)
    new_label = apply_op("class_center_sample",
                         lambda l: jnp.asarray(remap)[l], label)
    return new_label, Tensor(jnp.asarray(sampled), stop_gradient=True)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """reference: F.margin_cross_entropy (ArcFace-style combined margin:
    cos(m1*theta + m2) - m3 on the target logit, then scaled CE)."""
    def fn(lg, lab):
        lab_ = lab.reshape(-1)
        theta = jnp.arccos(jnp.clip(lg, -1.0, 1.0))
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(lab_, lg.shape[-1], dtype=lg.dtype)
        adjusted = jnp.where(onehot > 0, tgt, lg) * scale
        logp = jax.nn.log_softmax(adjusted, axis=-1)
        loss = -jnp.take_along_axis(logp, lab_[:, None], axis=-1)
        if reduction == "mean":
            loss_out = jnp.mean(loss)
        elif reduction == "sum":
            loss_out = jnp.sum(loss)
        else:
            loss_out = loss
        if return_softmax:
            return loss_out, jax.nn.softmax(adjusted, -1)
        return loss_out
    return apply_op("margin_cross_entropy", fn, logits, label)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """reference: F.adaptive_log_softmax_with_loss (Grave et al. adaptive
    softmax): head cluster + tail clusters with projection pairs."""
    def fn(x, lab, hw, *rest):
        n_clusters = len(cutoffs)
        if head_bias is not None:
            hb = rest[-1]
            tails = rest[:-1]
        else:
            hb = None
            tails = rest
        head_logits = x @ hw.T + (hb if hb is not None else 0.0)
        head_logp = jax.nn.log_softmax(head_logits, -1)
        shortlist = cutoffs[0]
        lab_ = lab.reshape(-1)
        out = jnp.zeros_like(lab_, dtype=x.dtype)
        # shortlist words
        in_short = lab_ < shortlist
        short_lp = jnp.take_along_axis(
            head_logp, jnp.clip(lab_, 0, shortlist - 1)[:, None], -1)[:, 0]
        out = jnp.where(in_short, short_lp, out)
        # tail clusters
        lo = shortlist
        for ci in range(n_clusters):
            hi = cutoffs[ci + 1] if ci + 1 < len(cutoffs) else None
            hi = hi if hi is not None else lab_.max() + 1
            proj, w = tails[2 * ci], tails[2 * ci + 1]
            z = (x @ proj.T) @ w.T
            lp = jax.nn.log_softmax(z, -1)
            rel = jnp.clip(lab_ - lo, 0, w.shape[0] - 1)
            cluster_lp = head_logp[:, shortlist + ci] + jnp.take_along_axis(
                lp, rel[:, None], -1)[:, 0]
            mask = (lab_ >= lo) & (lab_ < hi)
            out = jnp.where(mask, cluster_lp, out)
            lo = hi
        loss = -out.mean()
        return out, loss
    args = [input, label, head_weight] + list(tail_weights) \
        + ([head_bias] if head_bias is not None else [])
    return apply_op("adaptive_log_softmax_with_loss", fn, *args)
