"""Parameter initializers (reference: python/paddle/nn/initializer/)."""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtype import to_jax_dtype
from ..core.tensor import Tensor, _val
from ..framework.random import next_key


class Initializer:
    def __call__(self, shape, dtype) -> jax.Array:
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        jd = to_jax_dtype(dtype)
        return (self.mean + self.std *
                jax.random.normal(next_key(), tuple(shape), jnp.float32)).astype(jd)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0, b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        jd = to_jax_dtype(dtype)
        out = jax.random.truncated_normal(next_key(), self.a, self.b,
                                          tuple(shape), jnp.float32)
        return (self.mean + self.std * out).astype(jd)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        jd = to_jax_dtype(dtype)
        return jax.random.uniform(next_key(), tuple(shape), jnp.float32,
                                  self.low, self.high).astype(jd)


def _fans(shape) -> tuple:
    shape = tuple(shape)
    if len(shape) < 2:
        f = shape[0] if shape else 1
        return f, f
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[0] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[1] * receptive if len(shape) > 2 else shape[1]
    # conv weights in this framework are [out_c, in_c, *k]
    if len(shape) > 2:
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    return fan_in, fan_out


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = jnp.asarray(_val(self.value), to_jax_dtype(dtype))
        assert tuple(v.shape) == tuple(shape), f"Assign shape {v.shape} != {shape}"
        return v


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        return jax.nn.initializers.orthogonal(self.gain)(
            next_key(), tuple(shape), jnp.float32).astype(to_jax_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype):
        w = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        for i in range(min(oc, ic * self.groups)):
            center = tuple(s // 2 for s in shape[2:])
            w[(i, i % ic) + center] = 1.0
        return jnp.asarray(w, to_jax_dtype(dtype))


def calculate_gain(nonlinearity: str, param=None) -> float:
    if nonlinearity == "tanh":
        return 5.0 / 3.0
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4.0
    return 1.0


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


_global_weight_init: Optional[Initializer] = None
_global_bias_init: Optional[Initializer] = None


class Bilinear(Initializer):
    """reference: paddle.nn.initializer.Bilinear — bilinear upsampling
    kernel init for (transposed) conv weights (C_out, C_in, kH, kW)."""

    def __call__(self, shape, dtype):
        import numpy as np
        shape = tuple(shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        kh, kw = shape[2], shape[3]
        fh, fw = (kh + 1) // 2, (kw + 1) // 2
        ch = (2 * fh - 1 - fh % 2) / (2.0 * fh)
        cw = (2 * fw - 1 - fw % 2) / (2.0 * fw)
        og = np.ogrid[:kh, :kw]
        filt = ((1 - abs(og[0] / fh - ch)) * (1 - abs(og[1] / fw - cw)))
        w = np.zeros(shape, np.float32)
        for i in range(min(shape[0], shape[1])):
            w[i, i] = filt
        return w.astype("float32")
