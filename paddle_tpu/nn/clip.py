"""Gradient clipping (reference: python/paddle/nn/clip.py).

ClipGradByGlobalNorm is hybrid-parallel aware through the
HybridParallelOptimizer, which sums partial norms across mp/pp/sharding
groups before scaling (see fleet/meta_optimizers/dygraph_optimizer).
"""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max),
                                  stop_gradient=True)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g._value.astype(jnp.float32) ** 2))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype),
                                  stop_gradient=True)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = clip_norm
        self.group_name = group_name
        self.auto_skip_clip = auto_skip_clip

    def global_norm_sq(self, grads) -> jnp.ndarray:
        """Sum of squared norms (before any cross-group reduction)."""
        total = jnp.zeros((), jnp.float32)
        for g in grads:
            if g is None:
                continue
            v = g._value if isinstance(g, Tensor) else g
            total = total + jnp.sum(v.astype(jnp.float32) ** 2)
        return total

    def __call__(self, params_grads):
        grads = [g for _, g in params_grads]
        total_sq = self.global_norm_sq(grads)
        return self.apply_with_norm_sq(params_grads, total_sq)

    def apply_with_norm_sq(self, params_grads, total_sq):
        global_norm = jnp.sqrt(total_sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype),
                                  stop_gradient=True)))
        return out

    # functional form for the jitted train step
    def clip_tree(self, grads_tree):
        import jax
        leaves = jax.tree.leaves(grads_tree)
        total = sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in leaves)
        global_norm = jnp.sqrt(total)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads_tree)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    total = jnp.sum(jnp.stack([
        jnp.sum(jnp.abs(p.grad._value.astype(jnp.float32)) ** norm_type)
        for p in params]))
    total_norm = total ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total_norm, 1e-6), 1.0)
    for p in params:
        p.grad._value = (p.grad._value * scale).astype(p.grad._value.dtype)
    return Tensor(total_norm)


def clip_grad_value_(parameters, clip_value):
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)
