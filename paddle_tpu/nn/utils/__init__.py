"""paddle.nn.utils (reference: python/paddle/nn/utils/): weight
reparameterizations + parameter/vector conversions + grad clipping."""

from __future__ import annotations

from typing import List

import numpy as np

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, _val
from ..layer import Layer


def parameters_to_vector(parameters, name=None) -> Tensor:
    """Concatenate flattened parameters (reference util of same name)."""
    vals = [_val(p).reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(vals), stop_gradient=True)


def vector_to_parameters(vec, parameters, name=None) -> None:
    v = _val(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p._value = v[off:off + n].reshape(tuple(p.shape)).astype(
            _val(p).dtype)
        off += n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False) -> Tensor:
    """In-place global-norm clip over .grad (reference:
    nn/utils/clip_grad_norm_)."""
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(_val(p.grad))) for p in params]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(_val(p.grad)) ** norm_type)
             for p in params])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite grad norm")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        g = p.grad
        g._value = _val(g) * scale
    return Tensor(total)


def clip_grad_value_(parameters, clip_value) -> None:
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(_val(p.grad), -clip_value, clip_value)


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0) -> Layer:
    """Reparameterize ``layer.<name>`` as g * v/||v|| (reference:
    nn/utils/weight_norm_hook.py). The derived weight is recomputed from
    the TRAINABLE v/g parameters in a forward pre-hook using tape-recorded
    tensor ops, so gradients flow to v and g."""
    import paddle_tpu as _paddle

    w = getattr(layer, name)
    wv = _val(w)
    axes = tuple(i for i in range(wv.ndim) if i != dim)
    g0 = jnp.sqrt(jnp.sum(wv * wv, axis=axes, keepdims=True))
    from ...core.tensor import Parameter
    v = Parameter(wv, name=f"{w.name}_v")
    g = Parameter(g0, name=f"{w.name}_g")
    layer.add_parameter(f"{name}_v", v)
    layer.add_parameter(f"{name}_g", g)
    # the original becomes derived — drop it from the parameter dict
    layer._parameters.pop(name, None)

    def recompute(lyr, inputs):
        # tensor ops (not raw jnp) so the tape links weight -> (v, g)
        norm = _paddle.sqrt(_paddle.sum(v * v, axis=list(axes),
                                        keepdim=True))
        object.__setattr__(lyr, name, g * v / norm)
        return None

    recompute(layer, None)
    helper = layer.register_forward_pre_hook(recompute)
    layer.__dict__[f"_{name}_weight_norm_hook"] = helper
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight") -> Layer:
    helper = layer.__dict__.pop(f"_{name}_weight_norm_hook", None)
    if helper is not None:
        helper.remove()
    v = layer._parameters.pop(f"{name}_v", None)
    g = layer._parameters.pop(f"{name}_g", None)
    if v is not None and g is not None:
        from ...core.tensor import Parameter
        vv, gg = _val(v), _val(g)
        axes = tuple(i for i in range(vv.ndim) if gg.shape[i] == 1)
        norm = jnp.sqrt(jnp.sum(vv * vv, axis=axes, keepdims=True))
        w = Parameter(gg * vv / jnp.maximum(norm, 1e-12),
                      name=v.name.replace("_v", ""))
        layer.__dict__.pop(name, None)
        layer.add_parameter(name, w)
    return layer


def spectral_norm(layer: Layer, name: str = "weight", n_power_iterations=1,
                  eps: float = 1e-12, dim: int = 0) -> Layer:
    """Hook-based spectral normalization of ``layer.<name>``
    (reference: nn/utils/spectral_norm_hook.py). The ORIGINAL weight
    stays the live trainable parameter (as ``<name>_orig``); every
    forward recomputes the normalized weight from its CURRENT value with
    tape-recorded ops, so the optimizer trains it and gradients flow
    through sigma (torch/paddle semantics)."""
    import paddle_tpu as _paddle

    w = layer._parameters.pop(name)
    layer.add_parameter(f"{name}_orig", w)
    wv = _val(w)
    h = wv.shape[dim]
    rng = np.random.default_rng(0)
    u0 = jnp.asarray(rng.standard_normal(h), jnp.float32)
    state = {"u": u0 / jnp.linalg.norm(u0)}
    perm = [dim] + [i for i in range(wv.ndim) if i != dim]

    def recompute(lyr, inputs):
        wv = _val(w)                         # CURRENT trained value
        wm = jnp.transpose(wv, perm).reshape(wv.shape[dim], -1)
        uu = state["u"]
        # n_power_iterations=0 is valid (use stored estimates): vv must
        # exist regardless
        vv = wm.T @ uu
        vv = vv / (jnp.linalg.norm(vv) + eps)
        for _ in range(n_power_iterations):
            uu = wm @ vv
            uu = uu / (jnp.linalg.norm(uu) + eps)
            vv = wm.T @ uu
            vv = vv / (jnp.linalg.norm(vv) + eps)
        if not isinstance(wv, jax.core.Tracer):
            state["u"] = uu
        # sigma via tensor ops on the Parameter so grads flow through it
        w_mat = w.transpose(perm).reshape([wv.shape[dim], -1])
        u_t = Tensor(uu, stop_gradient=True)
        v_t = Tensor(vv, stop_gradient=True)
        sigma = _paddle.matmul(_paddle.matmul(u_t.unsqueeze(0), w_mat),
                               v_t.unsqueeze(-1)).reshape([])
        object.__setattr__(lyr, name, w / sigma)
        return None

    recompute(layer, None)
    layer.register_forward_pre_hook(recompute)
    return layer
