"""paddle.linalg namespace (reference: python/paddle/linalg.py re-exports
the tensor.linalg surface)."""
from .ops.linalg import *  # noqa: F401,F403
from .ops.linalg import (cond, cov, corrcoef, eig, eigh, eigvals,  # noqa: F401
                         eigvalsh, det, slogdet, inv, inverse, pinv, solve,
                         lstsq, lu, lu_unpack, qr, svd, svdvals,
                         matrix_power, matrix_rank, cholesky,
                         cholesky_solve, triangular_solve, multi_dot,
                         matrix_exp, householder_product, norm)
