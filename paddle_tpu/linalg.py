"""paddle.linalg namespace (reference: python/paddle/linalg.py re-exports
the tensor.linalg surface)."""
import jax.numpy as jnp
from .core.tensor import apply_op, _val
from .ops.linalg import *  # noqa: F401,F403
from .ops.linalg import (cond, cov, corrcoef, eig, eigh, eigvals,  # noqa: F401
                         eigvalsh, det, slogdet, inv, inverse, pinv, solve,
                         lstsq, lu, lu_unpack, qr, svd, svdvals,
                         matrix_power, matrix_rank, cholesky,
                         cholesky_solve, triangular_solve, multi_dot,
                         matrix_exp, householder_product, norm)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """reference: paddle.linalg.vector_norm."""
    def fn(a):
        return jnp.linalg.vector_norm(a, ord=p, axis=axis,
                                      keepdims=keepdim)
    return apply_op("vector_norm", fn, x)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """reference: paddle.linalg.matrix_norm."""
    def fn(a):
        m = jnp.moveaxis(a, axis, (-2, -1)) if axis != (-2, -1) else a
        out = jnp.linalg.matrix_norm(m, ord=p, keepdims=keepdim)
        return out
    return apply_op("matrix_norm", fn, x)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """reference: paddle.linalg.svd_lowrank — randomized low-rank SVD
    (Halko et al. subspace iteration)."""
    import jax as _jax
    from .framework.random import next_key

    def fn(a):
        m = a if M is None else a - _val(M)
        n = m.shape[-1]
        g = _jax.random.normal(next_key(), m.shape[:-2] + (n, q),
                               jnp.float32).astype(m.dtype)
        y = m @ g
        for _ in range(niter):
            y = m @ (jnp.swapaxes(m, -2, -1) @ y)
        qmat, _ = jnp.linalg.qr(y)
        b = jnp.swapaxes(qmat, -2, -1) @ m
        u_b, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qmat @ u_b, s, jnp.swapaxes(vh, -2, -1)
    return apply_op("svd_lowrank", fn, x)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """reference: paddle.linalg.pca_lowrank."""
    v = _val(x)
    k = q if q is not None else min(6, *v.shape[-2:])

    def fn(a):
        m = a - jnp.mean(a, axis=-2, keepdims=True) if center else a
        return m
    centered = apply_op("pca_center", fn, x)
    return svd_lowrank(centered, q=k, niter=niter)
