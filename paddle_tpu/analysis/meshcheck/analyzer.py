"""Orchestration: parse (or reuse a parse), build the SPMD context, run
the MSH rules.

``analyze_package`` mirrors tracecheck's entry point and accepts the
same :class:`ParsedPackage` so the unified CLI (tools/analyze.py) runs
both suites over ONE ast.parse pass.  The context build is strictly
read-only over the shared ``ModuleInfo`` objects — running meshcheck
never changes what tracecheck reports on the same parse, in either
order.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..tracecheck.analyzer import ParsedPackage, parse_package
from ..tracecheck.callgraph import CallGraph
from ..tracecheck.findings import (Finding, dedupe_findings,
                                   parse_pragmas, suppressed)
from .mesh_model import build_context
from . import rules as MR


@dataclass
class AnalyzerConfig:
    exclude_patterns: tuple = ()
    rules: tuple = ("MSH001", "MSH002", "MSH003", "MSH004", "MSH005",
                    "MSH006")


@dataclass
class AnalysisResult:
    findings: List[Finding]              # post-pragma, pre-baseline
    suppressed: List[Finding]            # pragma-silenced
    n_files: int = 0
    n_functions: int = 0
    n_spmd: int = 0                      # per-shard / collective-bearing
    n_collective_sites: int = 0
    errors: List[str] = field(default_factory=list)


_RULE_FNS = {
    "MSH001": MR.msh001_axis_binding,
    "MSH002": MR.msh002_collective_under_tensor_branch,
    "MSH003": MR.msh003_divergent_sequences,
    "MSH004": MR.msh004_permute_discipline,
    "MSH005": MR.msh005_rank_divergent_trace,
    "MSH006": MR.msh006_host_callbacks,
}


def analyze_package(package_path: str,
                    config: Optional[AnalyzerConfig] = None,
                    parsed: Optional[ParsedPackage] = None
                    ) -> AnalysisResult:
    config = config or AnalyzerConfig()
    if parsed is None:
        parsed = parse_package(package_path, config.exclude_patterns)
    else:
        parsed = parsed.filtered(config.exclude_patterns)

    result = AnalysisResult(findings=[], suppressed=[])
    result.errors = list(parsed.errors)
    result.n_files = parsed.n_files

    graph = CallGraph(parsed.modules, parsed.package)
    ctx = build_context(parsed.modules, graph)
    result.n_spmd = len(ctx.spmd_fns)
    result.n_collective_sites = sum(
        len(v) for v in ctx.collectives.values())

    findings: List[Finding] = []
    for mod in parsed.modules.values():
        pragmas = parse_pragmas(mod.source_lines, tool="meshcheck")
        for fi in mod.functions.values():
            result.n_functions += 1
            batch: List[Finding] = []
            for code in config.rules:
                fn = _RULE_FNS.get(code)
                if fn is not None:
                    batch += fn(fi, ctx)
            for f in batch:
                (result.suppressed if suppressed(f, pragmas)
                 else findings).append(f)

    result.findings = dedupe_findings(findings)
    return result
