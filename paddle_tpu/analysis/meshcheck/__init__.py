"""meshcheck — an SPMD collective-discipline static analyzer.

tracecheck (r08) gates *trace* discipline; meshcheck gates the bug
class the sharded serving/training push multiplies: collectives whose
correctness depends on every member of a mesh axis agreeing on WHAT to
issue and WHEN.  Megatron-LM-style tensor/pipeline parallelism and the
GSPMD line of work both treat collective-order agreement across ranks
as the invariant everything rests on — and the failure mode is the
worst kind: a single-host test passes while the multi-process run
deadlocks every host with no traceback.

Rules (all pure AST over the shared tracecheck parse):

- **MSH001** collective over an axis name bound by no enclosing
  mesh/shard_map and absent from the topology vocabulary (extracted
  from ``fleet/base_topology._HYBRID_AXES``, so dp/pp/sharding/sep/mp
  are first-class); includes group ``.axis_name`` reads that ignore
  ``.global_axis``.
- **MSH002** collective reachable under tensor-valued ``if``/``while``
  (divergent-collective deadlock; reuses TRC006's predicate
  classifier, so static shape/dtype predicates are exempt).
- **MSH003** exclusive branches issuing different collective sequences
  on a rank-dependent predicate (order-divergence hang).
- **MSH004** unpaired p2p/``ppermute`` discipline: permutes under
  ``lax.cond``/``switch`` branches, eager send/recv under
  rank-conditional guards.
- **MSH005** rank/process-id-dependent Python branching in
  collective-issuing code (host-divergent trace -> mismatched
  programs).
- **MSH006** host callbacks/telemetry inside shard_map bodies
  (composes with TRC007).

Findings support inline ``# meshcheck: disable=MSH00x`` pragmas and a
checked-in baseline (tools/meshcheck_baseline.json); the tier-1 test
gates NEW findings only.

Run it locally::

    python tools/analyze.py                    # tracecheck + meshcheck
    python tools/analyze.py --suite meshcheck
    python tools/analyze.py --update-baseline
"""

from ..tracecheck.findings import (Finding, fingerprint, load_baseline,
                                   subtract_baseline, write_baseline)
from .analyzer import AnalyzerConfig, AnalysisResult, analyze_package
from .rules import MESH_RULES

__all__ = [
    "AnalyzerConfig", "AnalysisResult", "Finding", "MESH_RULES",
    "analyze_package", "fingerprint", "load_baseline",
    "subtract_baseline", "write_baseline",
]
