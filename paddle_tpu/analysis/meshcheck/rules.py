"""The MSH rule checkers.

Each rule is ``(FunctionInfo, SpmdContext) -> List[Finding]`` over ONE
function body (nested defs are their own FunctionInfo).  The rules
encode the SPMD contract Megatron-LM/GSPMD-style systems rest on: every
member of a mesh axis must issue the SAME collective sequence in the
SAME order — so axis names must resolve, collectives may not hide under
divergent control flow, and p2p permutes must be issued by every shard
unconditionally.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Tuple

from ..tracecheck import rules as R
from ..tracecheck.callgraph import callee_name
from ..tracecheck.findings import Finding
from .mesh_model import (PERMUTE_TAILS, SpmdContext, classify_collective,
                         is_p2p_call)

MESH_RULES: Dict[str, str] = {
    "MSH001": "collective over an axis name that is neither a topology "
              "axis (fleet/base_topology._HYBRID_AXES) nor bound by a "
              "mesh/shard_map declared in the module — resolves only by "
              "accident, and a group's .axis_name read without "
              ".global_axis addresses the wrong mesh axis for "
              "topology-derived groups",
    "MSH002": "collective reachable under a tensor-valued Python "
              "if/while in per-shard code — shards concretize the "
              "predicate differently (or trace fails), so only some "
              "members issue the collective: every host deadlocks at "
              "the first mismatched collective",
    "MSH003": "mutually exclusive branches issue DIFFERENT collective "
              "sequences on a rank-dependent predicate — members of the "
              "axis disagree on the order of collectives and the mesh "
              "hangs at the first mismatch; hoist collectives out of "
              "the branch or make the sequences identical",
    "MSH004": "unpaired point-to-point discipline: a "
              "ppermute/shift/send/recv issued under divergent control "
              "flow (lax.cond/switch branch, or a rank-conditional "
              "Python guard) — a permute only some shards issue, or a "
              "send whose matching recv is built by a different "
              "conditional, hangs the pipeline; issue permutes "
              "unconditionally each tick (zbh1 idiom) and pair "
              "send/recv keys by construction",
    "MSH005": "rank/process-id-dependent Python branching in "
              "collective-issuing code — each process traces a "
              "DIFFERENT program, so compiled collective schedules "
              "disagree across hosts; use traced lax.cond + masked "
              "psum (zbh1 idiom) or hoist the branch out of the traced "
              "region",
    "MSH006": "host callback or telemetry write inside a shard_map "
              "body — runs per shard per step on every host (TRC007's "
              "trace-time hazard compounded by mesh fan-out) and can "
              "desynchronize the per-shard schedule; record at the "
              "dispatch boundary instead",
}

_RANKISH_CALL_TAILS = {"axis_index", "process_index", "get_rank",
                       "get_stage_id", "get_group_rank", "axis_rank",
                       "get_local_rank", "get_data_parallel_rank",
                       "get_model_parallel_rank",
                       "get_sharding_parallel_rank",
                       "get_sep_parallel_rank", "is_first_stage",
                       "is_last_stage"}

_RANKISH_IDENT = re.compile(
    r"(^rank$|_rank$|^rank_|stage_id|first_stage|last_stage|"
    r"^is_first$|^is_last$|^global_rank$|^proc_id$|^process_index$)")


def _finding(fi, node: ast.AST, rule: str, msg: str) -> Finding:
    line = getattr(node, "lineno", fi.lineno)
    return Finding(rule=rule, path=fi.module.relpath, line=line,
                   func=fi.qualname, message=msg,
                   source=fi.module.line(line))


def _calls_in_order(node: ast.AST) -> Iterator[ast.Call]:
    """Pre-order call sites, never entering nested function defs."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        return
    if isinstance(node, ast.Call):
        yield node
    for child in ast.iter_child_nodes(node):
        yield from _calls_in_order(child)


def _rankish_test(test: ast.expr) -> Optional[str]:
    """Does this predicate read a rank/stage/process identity?  Returns
    the identifying name, or None."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            name = callee_name(node)
            if name and name.rsplit(".", 1)[-1] in _RANKISH_CALL_TAILS:
                return name
        elif isinstance(node, ast.Name):
            if _RANKISH_IDENT.search(node.id.lower()):
                return node.id
        elif isinstance(node, ast.Attribute):
            if _RANKISH_IDENT.search(node.attr.lower()):
                return node.attr
    return None


def _if_statements(fi) -> Iterator[ast.stmt]:
    """If/While statements of this function body (not nested defs)."""
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return
    stack: List[ast.AST] = list(fi.node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, (ast.If, ast.While)):
            yield node
        stack.extend(ast.iter_child_nodes(node))


# ------------------------------------------------------------------ MSH001
def _param_default(fi, name: str) -> Tuple[bool, Optional[str]]:
    """(is_parameter, string_default_or_None), searching enclosing
    scopes so a nested helper sees the outer function's signature."""
    scope = fi
    while scope is not None:
        node = scope.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            pos = list(a.posonlyargs) + list(a.args)
            n_def = len(a.defaults)
            for i, p in enumerate(pos):
                if p.arg != name:
                    continue
                di = i - (len(pos) - n_def)
                d = a.defaults[di] if di >= 0 else None
                return True, (d.value if isinstance(d, ast.Constant)
                              and isinstance(d.value, str) else None)
            for p, d in zip(a.kwonlyargs, a.kw_defaults):
                if p.arg == name:
                    return True, (d.value if isinstance(d, ast.Constant)
                                  and isinstance(d.value, str) else None)
            if name in {x.arg for x in (a.vararg, a.kwarg) if x}:
                return True, None
        scope = scope.parent
    return False, None


def _axis_names_of(fi, node: ast.expr) -> List[Tuple[str, str]]:
    """Statically-known axis names an axis argument denotes:
    [(name, provenance), ...]."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [(node.value, "")]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append((e.value, ""))
        return out
    if isinstance(node, ast.Name):
        is_param, default = _param_default(fi, node.id)
        if is_param:
            if default is not None:
                return [(default, f" (default of parameter "
                                  f"'{node.id}')")]
            return []
        # simple local binding: name = "literal"
        if not isinstance(fi.node, (ast.Module, ast.Lambda)):
            for stmt in R._flatten_statements(list(fi.node.body)):
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Constant) and \
                        isinstance(stmt.value.value, str) and \
                        any(isinstance(t, ast.Name) and t.id == node.id
                            for t in stmt.targets):
                    return [(stmt.value.value, f" (via '{node.id}')")]
    return []


def _axis_bound(fi, ctx: SpmdContext, name: str) -> bool:
    if name in ctx.topology_axes:
        return True
    mp = ctx.graph.modpath_of(fi.module)
    return name in ctx.module_axes.get(mp, ())


def _group_axis_reads(fi) -> List[Finding]:
    """A group's ``.axis_name`` read without consulting
    ``.global_axis`` (and without pairing it with the group's OWN
    ``.mesh``): topology-derived groups address collectives by their
    GLOBAL mesh axis — ``communication.group.resolve_group_axis`` is
    the sanctioned resolver."""
    reads: List[Tuple[str, ast.AST]] = []
    mentions_global = False
    mesh_objs = set()
    for node in R._body_walk(fi):
        if isinstance(node, ast.Attribute):
            if node.attr == "global_axis":
                mentions_global = True
            elif node.attr in ("mesh", "get_mesh") and \
                    isinstance(node.value, ast.Name):
                mesh_objs.add(node.value.id)
            elif node.attr == "axis_name" and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id not in ("self", "cls") and \
                    isinstance(node.ctx, ast.Load):
                reads.append((node.value.id, node))
        elif isinstance(node, ast.Constant) and node.value == "global_axis":
            mentions_global = True
        elif isinstance(node, ast.Call):
            name = callee_name(node)
            if name == "getattr" and len(node.args) >= 2 and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id not in ("self", "cls") and \
                    isinstance(node.args[1], ast.Constant) and \
                    node.args[1].value == "axis_name":
                reads.append((node.args[0].id, node))
    if mentions_global or not reads:
        return []
    seen = set()
    out = []
    for obj, node in reads:
        if obj in mesh_objs or obj in seen:
            continue            # paired with the group's own 1-D mesh
        seen.add(obj)
        out.append(_finding(
            fi, node, "MSH001",
            f"'{obj}.axis_name' resolved without consulting "
            f"'{obj}.global_axis' — a group derived from a topology "
            "axis addresses collectives by its GLOBAL mesh axis "
            "(dp/mp/pp/...), not its private 1-D mesh name; use "
            "communication.group.resolve_group_axis (global_axis "
            "first, then axis_name)"))
    return out


def msh001_axis_binding(fi, ctx: SpmdContext) -> List[Finding]:
    out: List[Finding] = []
    for site in ctx.collectives.get(id(fi), ()):
        if site.axis_node is None:
            continue
        for name, how in _axis_names_of(fi, site.axis_node):
            if _axis_bound(fi, ctx, name):
                continue
            out.append(_finding(
                fi, site.call, "MSH001",
                f"collective {site.tail}(...) over axis '{name}'{how} — "
                f"not a topology axis "
                f"({'/'.join(sorted(ctx.topology_axes))}) and not bound "
                "by any mesh/shard_map declared in this module; the "
                "name resolves only if some caller binds it, and a "
                "multi-process run hangs or fails where a single-host "
                "test cannot see it"))
    out.extend(_group_axis_reads(fi))
    return out


# ------------------------------------------------------------------ MSH002
def msh002_collective_under_tensor_branch(fi, ctx: SpmdContext
                                          ) -> List[Finding]:
    if id(fi) not in ctx.spmd_fns or \
            isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    tainted: set = set()
    out: List[Finding] = []
    for stmt in R._flatten_statements(list(fi.node.body)):
        if isinstance(stmt, ast.Assign):
            desc = R._tensorish(fi, stmt.value, tainted)
            for c in R._assigned_chains(stmt):
                if "." not in c:
                    (tainted.add(c) if desc else tainted.discard(c))
        if not isinstance(stmt, (ast.If, ast.While)):
            continue
        if R._test_has_tracer_guard(stmt.test):
            continue
        desc = R._tensorish(fi, stmt.test, tainted)
        if desc is None:
            continue
        kind = "while" if isinstance(stmt, ast.While) else "if"
        for blk in R._sub_blocks(stmt):
            for s2 in R._flatten_statements(blk):
                for call in R._header_calls(s2):
                    site = classify_collective(fi, call, ctx.graph)
                    if site is not None and not site.query_only:
                        out.append(_finding(
                            fi, call, "MSH002",
                            f"collective {site.tail}(...) under "
                            f"tensor-valued `{kind}` ({desc}) — shards "
                            "concretize the predicate independently, so "
                            "only some members issue the collective and "
                            "every host deadlocks at the first "
                            "mismatch; use lax.cond with the collective "
                            "hoisted out, or mask with jnp.where"))
                        continue
                    if any(id(c) in ctx.reaches
                           for c in ctx.graph.resolve_call(fi, call)):
                        out.append(_finding(
                            fi, call, "MSH002",
                            f"call under tensor-valued `{kind}` ({desc}) "
                            "reaches collectives — divergent-collective "
                            "deadlock; hoist the collective-bearing "
                            "call out of the branch"))
    return out


# ------------------------------------------------------------------ MSH003
def _collective_sequence(fi, stmts, ctx: SpmdContext
                         ) -> List[Tuple[str, str]]:
    """Ordered (op, axis) sequence a statement list issues: direct
    collectives plus one level of resolved same-package calls."""
    seq: List[Tuple[str, str]] = []
    for stmt in stmts:
        for call in _calls_in_order(stmt):
            site = classify_collective(fi, call, ctx.graph)
            if site is not None:
                if site.query_only:
                    continue
                names = _axis_names_of(fi, site.axis_node) \
                    if site.axis_node is not None else []
                seq.append((site.tail,
                            names[0][0] if names else "?"))
                continue
            for callee in ctx.graph.resolve_call(fi, call):
                for sub in ctx.collectives.get(id(callee), ()):
                    if sub.query_only:
                        continue
                    names = _axis_names_of(callee, sub.axis_node) \
                        if sub.axis_node is not None else []
                    seq.append((sub.tail,
                                names[0][0] if names else "?"))
    return seq


def msh003_divergent_sequences(fi, ctx: SpmdContext) -> List[Finding]:
    if id(fi) not in ctx.spmd_fns and id(fi) not in ctx.reaches:
        return []
    out: List[Finding] = []
    for stmt in _if_statements(fi):
        if not isinstance(stmt, ast.If) or not stmt.orelse:
            continue
        why = _rankish_test(stmt.test)
        if why is None:
            continue            # uniform/static predicates are sound
        seq_a = _collective_sequence(fi, stmt.body, ctx)
        seq_b = _collective_sequence(fi, stmt.orelse, ctx)
        if seq_a == seq_b or not (seq_a or seq_b):
            continue

        def fmt(seq):
            return "[" + ", ".join(f"{t}@{a}" for t, a in seq) + "]"

        out.append(_finding(
            fi, stmt, "MSH003",
            f"exclusive branches on rank-dependent predicate ({why}) "
            f"issue different collective sequences: {fmt(seq_a)} vs "
            f"{fmt(seq_b)} — members of the axis disagree on collective "
            "order and hang at the first mismatch; issue the same "
            "sequence on both paths (mask unused results) or hoist the "
            "collectives above the branch"))
    return out


# ------------------------------------------------------------------ MSH004
def msh004_permute_discipline(fi, ctx: SpmdContext) -> List[Finding]:
    out: List[Finding] = []
    # (a) permute inside a lax.cond/switch branch: divergent issuance
    if id(fi) in ctx.cond_reach:
        for site in ctx.collectives.get(id(fi), ()):
            if site.tail in PERMUTE_TAILS:
                out.append(_finding(
                    fi, site.call, "MSH004",
                    f"{site.tail}(...) inside a lax.cond/switch branch "
                    "— only the shards taking this branch issue the "
                    "permute, and collective-permute requires every "
                    "member of the axis each step; issue it "
                    "unconditionally outside the branch and mask the "
                    "payload instead (zbh1 tick idiom)"))
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return out

    # (b) eager p2p issued under a rank-conditional guard
    def flag(call, how):
        out.append(_finding(
            fi, call, "MSH004",
            f"p2p {callee_name(call)}(...) {how} — pairing of sends "
            "and recvs is decided by per-rank host control flow, so a "
            "mismatched branch strands the peer; derive both endpoints "
            "of every transfer from the topology so keys pair by "
            "construction (and keep the pairing under test)"))

    def scan(stmts: List[ast.stmt], active: Optional[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                why = _rankish_test(stmt.test)
                if why is not None:
                    for blk in (stmt.body, stmt.orelse):
                        for s2 in blk:
                            for call in _calls_in_order(s2):
                                if is_p2p_call(fi, call, ctx.graph):
                                    flag(call, "issued under the "
                                         f"rank-conditional branch "
                                         f"({why})")
                    if any(isinstance(s, ast.Return) for s in stmt.body):
                        active = why
                    continue
            if active is not None:
                for call in _calls_in_order(stmt):
                    if is_p2p_call(fi, call, ctx.graph):
                        flag(call, "guarded by a rank-conditional "
                             f"early return ({active})")
            else:
                for blk in R._sub_blocks(stmt):
                    scan(blk, active)

    scan(list(fi.node.body), None)
    return out


# ------------------------------------------------------------------ MSH005
def msh005_rank_divergent_trace(fi, ctx: SpmdContext) -> List[Finding]:
    if id(fi) not in ctx.reaches or \
            isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    out: List[Finding] = []
    for stmt in _if_statements(fi):
        why = _rankish_test(stmt.test)
        if why is None:
            continue
        kind = "while" if isinstance(stmt, ast.While) else "if"
        out.append(_finding(
            fi, stmt, "MSH005",
            f"Python `{kind}` on rank/process identity ({why}) in "
            "collective-issuing code — each process traces a DIFFERENT "
            "program, so compiled collective schedules disagree across "
            "hosts; use lax.cond on a traced axis_index + masked psum "
            "(zbh1 idiom) or hoist the branch out of the traced region"))
    return out


# ------------------------------------------------------------------ MSH006
_CALLBACK_TAILS = {"pure_callback", "io_callback"}
_DEBUG_TAILS = {"print", "callback", "breakpoint"}


def msh006_host_callbacks(fi, ctx: SpmdContext) -> List[Finding]:
    if id(fi) not in ctx.shardmap_reach:
        return []
    out: List[Finding] = []
    for node in R._body_walk(fi):
        if not isinstance(node, ast.Call):
            continue
        name = callee_name(node)
        if name is None:
            continue
        parts = name.split(".")
        tail = parts[-1]
        hit = (tail in _CALLBACK_TAILS
               or ("debug" in parts[:-1] and tail in _DEBUG_TAILS)
               or "host_callback" in parts)
        if hit:
            out.append(_finding(
                fi, node, "MSH006",
                f"host callback {name}(...) inside a shard_map body — "
                "executes per shard per step on every host and can "
                "desynchronize the per-shard schedule; move it to the "
                "dispatch boundary (or jax.debug outside the manual "
                "region)"))
    for node, name in R._telemetry_writes(fi):
        out.append(_finding(
            fi, node, "MSH006",
            f"telemetry write {name}(...) inside a shard_map body — "
            "host-side state mutated per shard per step (TRC007's "
            "hazard compounded by mesh fan-out); record at the "
            "dispatch boundary"))
    return out
