"""The SPMD model meshcheck reasons over (pure AST, shared parse).

Three questions drive the MSH rules:

1. **Which calls are named-axis collectives, and what axis do they
   address?**  ``jax.lax`` collectives (psum/all_gather/ppermute/
   all_to_all/...) plus the repo's own wrappers
   (``communication/in_jit.py``, ``layers/mpu/mp_ops.py``) — each with
   the position of its axis-name argument.

2. **What axis names exist?**  The topology vocabulary is extracted from
   ``fleet/base_topology.py``'s ``_HYBRID_AXES`` (dp/pp/sharding/sep/mp
   are first-class), extended per module by axes declared in
   ``Mesh(...)``/``shard_map(axis_names=...)``/``pmap(axis_name=...)``/
   ``PartitionSpec`` literals — a module that builds its own mesh binds
   its own names.

3. **Which functions run per-shard / under divergent control flow?**
   Functions passed to ``shard_map``/``pmap`` (and their callees) are
   shard_map bodies; functions passed as ``lax.cond``/``switch``
   branches run divergently per shard; any function that (transitively)
   issues a named-axis collective is per-shard by definition —
   collectives are only legal inside a manual mesh region.

Everything here is READ-ONLY over the shared :class:`ModuleInfo` objects
so running meshcheck never perturbs a tracecheck pass on the same parse
(tracecheck's ``traced``/``trace_root`` flags are its own).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..tracecheck.callgraph import (CallGraph, FunctionInfo, ModuleInfo,
                                    callee_name, is_wrapper_decorator,
                                    wrapper_positions)

# fallback when base_topology.py is outside the analyzed path
AXIS_FALLBACK = ("dp", "pp", "sharding", "sep", "mp")

# jax.lax named-axis collectives: terminal name -> positional index of
# the axis-name argument.  axis_index IS a collective for binding
# purposes (unbound name fails / divergent value) even though it moves
# no data.  lax.pcast/psum2-style vma bookkeeping is excluded: it
# compiles to nothing and is sound under divergence.
LAX_COLLECTIVES: Dict[str, int] = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "ppermute": 1, "pshuffle": 1, "all_to_all": 1,
    "axis_index": 0, "pbroadcast": 1,
}
# static mesh-shape queries: MSH001 binding check only — never data
# movement, so MSH002-005 ignore them
AXIS_QUERIES: Dict[str, int] = {"axis_size": 0}

# point-to-point / permutation collectives (MSH004 discipline)
PERMUTE_TAILS = {"ppermute", "pshuffle", "shift_right", "shift_left"}

# repo collective wrappers, resolved through the call graph so aliasing
# never fools the match: (module-relpath substring, name -> axis pos)
WRAPPER_TABLES: Tuple[Tuple[str, Dict[str, int]], ...] = (
    ("communication/in_jit", {
        "all_reduce": 2, "all_gather": 1, "reduce_scatter": 1,
        "all_to_all": 1, "ppermute": 1, "shift_right": 1, "shift_left": 1,
        "broadcast": 2, "pgather": 1, "axis_rank": 0, "axis_size": 0,
    }),
    ("layers/mpu/mp_ops", {
        "_mp_allreduce": 1, "_c_split": 1, "_c_concat": 1,
        "_reduce_scatter": 1, "_all_gather": 1, "_parallel_matmul": 2,
        "_parallel_embedding": 2,
    }),
    ("utils/sequence_parallel_utils", {
        "scatter": 1, "gather": 1, "all_gather": 1, "reduce_scatter": 1,
    }),
)

# eager p2p surface (mailbox send/recv family) for MSH004's
# rank-conditional-issuance check
P2P_TAILS = {"send", "recv", "isend", "irecv"}

# axis-declaring constructors: any string constant inside their call is
# a locally-bound axis name for this module
_AXIS_BINDERS = {"Mesh", "AbstractMesh", "abstract_mesh", "shard_map",
                 "pmap", "PartitionSpec", "P", "NamedSharding"}

_SHARD_MAP_TAILS = {"shard_map", "pmap"}
# divergent-branch positions: cond's two branch callables; switch takes
# its branches as ONE sequence at position 1 (_wrapper_arg_fns unpacks
# list/tuple arguments) — positions 2+ are operands, not callables
_COND_TAILS = {"cond": (1, 2), "switch": (1,)}


@dataclass
class CollectiveSite:
    call: ast.Call
    tail: str                      # canonical op name (psum, ppermute, ...)
    axis_node: Optional[ast.expr]  # the axis-name argument, if present
    query_only: bool = False       # axis_size-style: binding check only


@dataclass
class SpmdContext:
    graph: CallGraph
    topology_axes: frozenset
    module_axes: Dict[str, Set[str]]            # modpath -> declared axes
    collectives: Dict[int, List[CollectiveSite]]  # id(fi) -> sites
    reaches: Set[int]          # id(fi): transitively issues a collective
    spmd_fns: Set[int]         # id(fi): runs per-shard (roots + closure)
    shardmap_reach: Set[int]   # id(fi): reachable from a shard_map body
    cond_reach: Set[int]       # id(fi): reachable from a cond/switch branch
    fn_of: Dict[int, FunctionInfo] = field(default_factory=dict)


def _is_lax_rooted(fi: FunctionInfo, name: str) -> bool:
    """'lax.psum' / 'jax.lax.psum' / bare 'psum' imported from jax.lax."""
    parts = name.split(".")
    if len(parts) == 1:
        imp = fi.module.imported_names.get(parts[0])
        return bool(imp and (imp[0] or "").endswith("lax"))
    if "lax" in parts[:-1]:
        return True
    root = fi.module.module_aliases.get(parts[0], "")
    return root.endswith("lax")


def _axis_argument(call: ast.Call, pos: int) -> Optional[ast.expr]:
    if pos < len(call.args):
        arg = call.args[pos]
        if not isinstance(arg, ast.Starred):
            return arg
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    return None


def classify_collective(fi: FunctionInfo, call: ast.Call,
                        graph: CallGraph) -> Optional[CollectiveSite]:
    """Is this call a named-axis collective (or axis query)?"""
    name = callee_name(call)
    if name is None:
        return None
    tail = name.rsplit(".", 1)[-1]
    if tail in LAX_COLLECTIVES and _is_lax_rooted(fi, name):
        return CollectiveSite(call, tail, _axis_argument(
            call, LAX_COLLECTIVES[tail]))
    if tail in AXIS_QUERIES and _is_lax_rooted(fi, name):
        return CollectiveSite(call, tail, _axis_argument(
            call, AXIS_QUERIES[tail]), query_only=True)
    # repo wrappers: resolve the callee, match by defining module
    for callee in graph.resolve_call(fi, call):
        rel = callee.module.relpath
        for hint, table in WRAPPER_TABLES:
            if hint in rel and callee.name in table:
                return CollectiveSite(call, callee.name, _axis_argument(
                    call, table[callee.name]))
    return None


def is_p2p_call(fi: FunctionInfo, call: ast.Call,
                graph: CallGraph) -> bool:
    """Eager mailbox p2p (send/recv/isend/irecv) out of the package's
    communication tree — by resolution or by alias into
    ``*.distributed``.  ``batch_isend_irecv`` counts too."""
    name = callee_name(call)
    if name is None:
        return False
    tail = name.rsplit(".", 1)[-1]
    if tail == "batch_isend_irecv":
        return True
    if tail not in P2P_TAILS:
        return False
    for callee in graph.resolve_call(fi, call):
        rel = callee.module.relpath
        if "communication/p2p" in rel or "communication/stream" in rel:
            return True
    parts = name.split(".")
    if len(parts) >= 2:
        target = fi.module.module_aliases.get(parts[0], "")
        if not target:
            imp = fi.module.imported_names.get(parts[0])
            target = f"{imp[0]}.{imp[1]}" if imp else ""
        return "distributed" in target
    imp = fi.module.imported_names.get(tail)
    return bool(imp and ("communication" in imp[0] or
                         "distributed" in imp[0]))


# ------------------------------------------------------------ vocabulary
def topology_axis_vocabulary(modules: Dict[str, ModuleInfo]) -> frozenset:
    """The hybrid-parallel axis names, read from base_topology.py's
    ``_HYBRID_AXES`` assignment (so a renamed/extended topology flows
    into the analyzer without code changes)."""
    for mod in modules.values():
        if not mod.relpath.endswith("fleet/base_topology.py"):
            continue
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                targets = [t.id for t in node.targets
                           if isinstance(t, ast.Name)]
                if "_HYBRID_AXES" in targets and isinstance(
                        node.value, (ast.Tuple, ast.List)):
                    names = [e.value for e in node.value.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str)]
                    if names:
                        return frozenset(names)
    return frozenset(AXIS_FALLBACK)


def module_declared_axes(mod: ModuleInfo) -> Set[str]:
    """Axis names this module binds itself: string constants inside
    ``Mesh``/``AbstractMesh``/``shard_map``/``pmap``/``PartitionSpec``
    construction calls — a module that builds a mesh over axis "x" may
    address "x"."""
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = callee_name(node)
        if name is None or name.rsplit(".", 1)[-1] not in _AXIS_BINDERS:
            continue
        for sub in ast.iter_child_nodes(node):
            for c in ast.walk(sub):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    out.add(c.value)
    return out


# ------------------------------------------------------------- the graph
def _local_named(mod: ModuleInfo, owner: Optional[FunctionInfo], n: str):
    scope = owner
    while scope is not None:
        hit = mod.functions.get(
            (scope.qualname + "." if scope.qualname else "") + n)
        if hit is not None:
            return hit
        scope = scope.parent
    return mod.functions.get(n)


def _wrapper_arg_fns(mod: ModuleInfo, owner: FunctionInfo,
                     call: ast.Call, positions: Tuple[int, ...],
                     lambda_by_pos: Dict[Tuple[int, int], FunctionInfo]
                     ) -> List[FunctionInfo]:
    """The function-valued arguments a wrapper call executes (Name,
    Lambda, or partial(f, ...)) — the execution edges reachability has
    to follow even though no direct call expression exists."""
    hits: List[FunctionInfo] = []
    args: List[ast.expr] = []
    for p in positions:
        if p >= len(call.args):
            continue
        arg = call.args[p]
        # lax.switch-style: the branches arrive as one list/tuple
        if isinstance(arg, (ast.List, ast.Tuple)):
            args.extend(arg.elts)
        else:
            args.append(arg)
    for arg in args:
        if isinstance(arg, ast.Lambda):
            hit = lambda_by_pos.get((arg.lineno, arg.col_offset))
            if hit:
                hits.append(hit)
        elif isinstance(arg, ast.Name):
            hit = _local_named(mod, owner if owner.qualname else None,
                               arg.id)
            if hit is not None:
                hits.append(hit)
        elif isinstance(arg, ast.Call):
            n = callee_name(arg)
            if n and n.rsplit(".", 1)[-1] == "partial" and arg.args and \
                    isinstance(arg.args[0], ast.Name):
                hit = _local_named(mod, owner if owner.qualname else None,
                                   arg.args[0].id)
                if hit is not None:
                    hits.append(hit)
    return hits


def build_context(modules: Dict[str, ModuleInfo],
                  graph: CallGraph) -> SpmdContext:
    topo = topology_axis_vocabulary(modules)
    module_axes = {mp: module_declared_axes(mod)
                   for mp, mod in modules.items()}

    fn_of: Dict[int, FunctionInfo] = {}
    collectives: Dict[int, List[CollectiveSite]] = {}
    edges: Dict[int, List[FunctionInfo]] = {}
    roots: Set[int] = set()
    shardmap_bodies: Set[int] = set()
    cond_branches: Set[int] = set()

    for mp, mod in modules.items():
        lambda_by_pos = {
            (f.node.lineno, f.node.col_offset): f
            for f in mod.functions.values()
            if isinstance(f.node, ast.Lambda)}
        for fi in mod.functions.values():
            fn_of[id(fi)] = fi
            # decorator roots, re-derived READ-ONLY (never consult
            # fi.trace_root: tracecheck mutates it during ITS analysis,
            # and sharing a parse must not make suite order observable)
            decs = getattr(fi.node, "decorator_list", ())
            if any(is_wrapper_decorator(d) for d in decs):
                roots.add(id(fi))
            sites: List[CollectiveSite] = []
            out_edges: List[FunctionInfo] = []
            for call in fi.calls:
                site = classify_collective(fi, call, graph)
                if site is not None:
                    sites.append(site)
                out_edges.extend(graph.resolve_call(fi, call))
                pos = wrapper_positions(call)
                if pos is not None:
                    name = callee_name(call) or ""
                    tail = name.rsplit(".", 1)[-1]
                    if tail in _COND_TAILS:
                        # branch callables only — the remaining switch
                        # positions are operands, not functions
                        pos = _COND_TAILS[tail]
                    arg_fns = _wrapper_arg_fns(mod, fi, call, pos,
                                               lambda_by_pos)
                    out_edges.extend(arg_fns)
                    roots.update(id(f) for f in arg_fns)
                    if tail in _SHARD_MAP_TAILS:
                        shardmap_bodies.update(id(f) for f in arg_fns)
                    if tail in _COND_TAILS:
                        cond_branches.update(id(f) for f in arg_fns)
            if sites:
                collectives[id(fi)] = sites
            edges[id(fi)] = out_edges

    def forward_closure(seed: Set[int]) -> Set[int]:
        out = set(seed)
        work = list(seed)
        while work:
            cur = work.pop()
            for callee in edges.get(cur, ()):
                if id(callee) not in out:
                    out.add(id(callee))
                    work.append(id(callee))
        return out

    # reverse closure: who transitively issues a DATA-MOVING collective
    # (query-only axis_size sites are static and sound under divergence
    # — they must not seed the divergent-deadlock reachability)
    rev: Dict[int, List[int]] = {}
    for src, outs in edges.items():
        for callee in outs:
            rev.setdefault(id(callee), []).append(src)
    moving = {fid for fid, sites in collectives.items()
              if any(not s.query_only for s in sites)}
    reaches = set(moving)
    work = list(reaches)
    while work:
        cur = work.pop()
        for caller in rev.get(cur, ()):
            if caller not in reaches:
                reaches.add(caller)
                work.append(caller)

    spmd = forward_closure(roots | moving)
    return SpmdContext(
        graph=graph, topology_axes=topo, module_axes=module_axes,
        collectives=collectives, reaches=reaches, spmd_fns=spmd,
        shardmap_reach=forward_closure(shardmap_bodies),
        cond_reach=forward_closure(cond_branches), fn_of=fn_of)
