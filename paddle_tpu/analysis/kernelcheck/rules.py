"""The KRN rule checkers.

Each rule is ``(FunctionInfo, KernelContext) -> List[Finding]`` over ONE
function (nested defs are their own FunctionInfo), mirroring the
tracecheck/meshcheck/faultcheck suites.  The rules encode the TPU
kernel discipline the r05–r17 Pallas arc relies on but has only ever
exercised in CPU interpret mode — tile alignment, the 16 MB VMEM
bound, grid/index-map hygiene, Mosaic-compilable kernel bodies,
f32 accumulation, and the ref-twin parity convention.

Shape dimensions are only judged when the static evaluator can prove
their value (module constants, literal locals, ``tile()`` calls) —
an unresolvable dimension is never a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..tracecheck import rules as R
from ..tracecheck.callgraph import FunctionInfo, callee_name
from ..tracecheck.findings import Finding
from ..tile_geometry import (DOUBLE_BUFFER, DTYPE_BYTES,
                             FUSED_DECODE_SCRATCH,
                             FUSED_DECODE_SINGLE_SCRATCH, LANES,
                             VMEM_LIMIT_BYTES, sublane_multiple)
from .geometry import (KernelContext, PallasSite, ScratchInfo, SpecInfo,
                       _module_consts, _scalar_assigns, eval_dim,
                       kernel_closure, map_arity, resolve_index_map_def)

KERNEL_RULES: Dict[str, str] = {
    "KRN001": "BlockSpec/scratch shape off the TPU tile grid — the "
              "minor-most (lane) dimension must be a multiple of 128 "
              "and the second-minor (sublane) dimension a multiple of "
              "the dtype's packing (8/f32, 16/bf16, 32/int8); "
              "misaligned blocks force Mosaic relayouts or fail to "
              "lower at all on hardware (interpret mode hides this)",
    "KRN002": "static VMEM budget — the site's block operands (double-"
              "buffered by Mosaic) plus persistent scratch must fit the "
              "16 MB per-core bound, and the fused-decode kernels' "
              "scratch lists must match the shared geometry templates "
              "(tile_geometry.py) the memwatch planner prices from — "
              "drift either way and planner and kernel disagree",
    "KRN003": "grid/index-map discipline — every index_map's arity must "
              "equal grid rank + num_scalar_prefetch, grid extents "
              "derived by floor division need a ceil-div or an explicit "
              "divisibility guard (a ragged tail silently drops "
              "otherwise), and index maps must return BLOCK indices, "
              "not element offsets (no multiplying by the block size)",
    "KRN004": "kernel-body purity — a Pallas kernel body must lower "
              "through Mosaic: no host/numpy/FLAGS/callback/clock "
              "calls, no Python while loops or data-dependent Python "
              "iteration (use lax.fori_loop / pl.when), no jnp ops "
              "known to have no Mosaic lowering (sort/unique/nonzero/"
              "quantile family); interpret mode happily runs all of "
              "these and hides the failure until a real TPU",
    "KRN005": "accumulation discipline — reduction carries must live in "
              "f32 scratch (not bf16/f16), dots must pin "
              "preferred_element_type (bf16/int8 inputs otherwise "
              "accumulate in low precision on the MXU), and scratch "
              "carried across grid steps needs a step-0 init under "
              "pl.when (stale VMEM from the previous grid cell "
              "otherwise leaks into the first accumulation)",
    "KRN006": "ref-twin census — every public pallas entry point needs "
              "a pure-jnp twin (<stem>_ref/_xla/_dense) as the parity "
              "oracle; a kernel without a ref twin cannot be validated "
              "in CPU CI and regressions surface only on hardware",
}

# KRN002 normalization: spellings the kernels use for dims the shared
# templates name symbolically (tile_geometry.fused_decode_env keys)
_SPELLINGS: Dict[str, str] = {
    "_LANES": "LANES",
    "nh * d": "qw",
    "nkv * d": "kvw",
}

# fused-decode entry -> the scratch template its site must match
_SCRATCH_TEMPLATES: Dict[str, Tuple[Tuple[str, ...], ...]] = {
    "fused_block_decode_pallas": FUSED_DECODE_SINGLE_SCRATCH,
    "fused_multi_block_decode_pallas": FUSED_DECODE_SCRATCH,
}

# jnp ops with no Mosaic lowering (value-dependent shapes / gather-
# heavy): fine under interpret, dead on hardware
_MOSAIC_UNSUPPORTED = {
    "sort", "argsort", "unique", "nonzero", "searchsorted", "bincount",
    "median", "quantile", "percentile",
}

_HOST_CALL_TAILS = {"print", "breakpoint", "input", "get_flag",
                    "snapshot", "pure_callback", "io_callback",
                    "host_callback"}
_HOST_MODULES = {"time", "random", "datetime", "os", "sys", "logging"}
_LOOP_ITER_TAILS = {"range", "enumerate", "zip", "reversed"}
_INIT_VALUE_TAILS = {"zeros", "zeros_like", "full", "full_like"}
_LOW_PRECISION = {"bfloat16", "bf16", "float16", "f16"}
_DOT_TAILS = {"dot_general", "dot", "matmul"}


def _finding(fi: FunctionInfo, node, rule: str, msg: str) -> Finding:
    line = getattr(node, "lineno", fi.lineno) or fi.lineno
    return Finding(rule=rule, path=fi.module.relpath, line=line,
                   func=fi.qualname, message=msg,
                   source=fi.module.line(line))


def _env(ctx: KernelContext, fi: FunctionInfo
         ) -> Tuple[Dict[str, int], Dict[str, List[ast.expr]]]:
    mp = fi.module.relpath
    consts = ctx.mod_consts.get(mp)
    if consts is None:
        consts = _module_consts(fi.module)
        ctx.mod_consts[mp] = consts
    return consts, _scalar_assigns(fi) if not isinstance(
        fi.node, (ast.Module, ast.Lambda)) else {}


def _sites_of(ctx: KernelContext, fi: FunctionInfo) -> List[PallasSite]:
    return [s for s in ctx.sites.get(fi.module.relpath, ())
            if s.fi is fi]


def _kernel_sites(ctx: KernelContext, fi: FunctionInfo
                  ) -> List[PallasSite]:
    """Sites whose KERNEL is this function (the gate for KRN004/005)."""
    return [s for s in ctx.sites.get(fi.module.relpath, ())
            if s.kernel is fi]


# ------------------------------------------------------------------ KRN001
def _check_shape(fi: FunctionInfo, shape: Sequence[ast.expr],
                 lineno: int, what: str, dtype: str,
                 consts: Dict[str, int],
                 assigns: Dict[str, List[ast.expr]]) -> List[Finding]:
    out: List[Finding] = []
    if not shape:
        return out
    anchor = shape[-1] if hasattr(shape[-1], "lineno") else None
    lane = eval_dim(shape[-1], consts, assigns)
    if lane is not None and lane % LANES != 0:
        out.append(_finding(
            fi, anchor or fi.node, "KRN001",
            f"{what} shape has minor-most dim {lane}, not a multiple "
            f"of the {LANES}-lane tile — Mosaic pads every such block "
            "to a full lane tile (or refuses the layout); make the "
            "last dim a multiple of 128, fold narrow columns into a "
            "wider block, or pragma a deliberate scalar/stat column "
            "with a reason"))
    if len(shape) >= 2:
        need = sublane_multiple(dtype) or 8   # 8 = min for any dtype
        sub = eval_dim(shape[-2], consts, assigns)
        if sub is not None and sub > 1 and sub % need != 0:
            dt = dtype or "any dtype"
            out.append(_finding(
                fi, anchor or fi.node, "KRN001",
                f"{what} shape has second-minor dim {sub}, not a "
                f"multiple of the sublane packing {need} for {dt} — "
                "the block straddles partial (sublane, lane) tiles; "
                "pad the dim (the -(-n // 8) * 8 idiom) or retile"))
    return out


def krn001_tile_alignment(fi: FunctionInfo, ctx: KernelContext
                          ) -> List[Finding]:
    mp = fi.module.relpath
    key = (mp, fi.qualname)
    specs = ctx.census_specs.get(key, ())
    scratch = ctx.census_scratch.get(key, ())
    if not specs and not scratch:
        return []
    consts, assigns = _env(ctx, fi)
    out: List[Finding] = []
    for s in specs:
        if s.shape is not None:
            out += _check_shape(fi, s.shape, s.lineno, "BlockSpec block",
                                "", consts, assigns)
    for sc in scratch:
        if sc.space == "SMEM" or sc.shape is None:
            continue                      # SMEM is scalar memory: untiled
        out += _check_shape(fi, sc.shape, sc.lineno,
                            f"VMEM scratch ({sc.dtype or 'unknown'})",
                            sc.dtype, consts, assigns)
    return out


# ------------------------------------------------------------------ KRN002
def _shape_bytes(shape: Optional[Sequence[ast.expr]], per_elem: int,
                 consts, assigns) -> Tuple[int, bool]:
    """(bytes, resolved) — resolved False means the shape made no claim
    and contributes 0 (an under-count, so any overrun is still real)."""
    if shape is None:
        return 0, False
    n = 1
    for d in shape:
        v = eval_dim(d, consts, assigns)
        if v is None:
            return 0, False
        n *= max(v, 0)
    return n * per_elem, True


def _norm_dim(expr: ast.expr) -> str:
    s = ast.unparse(expr)
    return _SPELLINGS.get(s, s)


def krn002_vmem_budget(fi: FunctionInfo, ctx: KernelContext
                       ) -> List[Finding]:
    sites = _sites_of(ctx, fi)
    if not sites:
        return []
    consts, assigns = _env(ctx, fi)
    out: List[Finding] = []
    for site in sites:
        # (a) literal pricing: streamed blocks double-buffered at 4 B
        # (the widest storage — an unresolvable block contributes 0, so
        # the sum is a LOWER bound and any overrun is real)
        total = 0
        unresolved = 0
        for spec in (site.in_specs or []) + (site.out_specs or []):
            b, ok = _shape_bytes(spec.shape, 4, consts, assigns)
            total += DOUBLE_BUFFER * b
            unresolved += 0 if ok else 1
        for sc in site.scratch or []:
            per = DTYPE_BYTES.get(sc.dtype, 4)
            b, ok = _shape_bytes(sc.shape, per, consts, assigns)
            total += b
            unresolved += 0 if ok else 1
        if total > VMEM_LIMIT_BYTES:
            mb = total / (1 << 20)
            extra = (f", {unresolved} shapes unresolved and uncounted"
                     if unresolved else "")
            out.append(_finding(
                fi, site.call, "KRN002",
                f"pallas_call working set is statically >= {mb:.1f} MB "
                f"(double-buffered blocks at 4 B/elem + scratch{extra})"
                f" — over the {VMEM_LIMIT_BYTES >> 20} MB per-core "
                "VMEM bound; shrink block tiles or split the kernel"))
        # (b) fused-decode scratch geometry must match the shared
        # template the memwatch planner prices from
        tmpl = _SCRATCH_TEMPLATES.get(fi.qualname)
        if tmpl is not None and site.scratch is not None:
            got = sorted(
                tuple(_norm_dim(d) for d in sc.shape)
                for sc in site.scratch if sc.shape is not None)
            want = sorted(tuple(t) for t in tmpl)
            if got != want:
                missing = [w for w in want if w not in got]
                extra = [g for g in got if g not in want]
                out.append(_finding(
                    fi, site.call, "KRN002",
                    f"scratch geometry of {fi.qualname} drifted from "
                    "the shared template "
                    "(tile_geometry.FUSED_DECODE_*SCRATCH) that "
                    "memwatch's plan_fused_layers prices VMEM from — "
                    f"template-only: {missing or '[]'}, kernel-only: "
                    f"{extra or '[]'}; update BOTH the kernel and the "
                    "template (and the planner test) together"))
    return out


# ------------------------------------------------------------------ KRN003
def _floordivs(expr: ast.expr) -> List[Tuple[ast.BinOp, List[ast.AST]]]:
    """(floordiv node, ancestor chain) pairs inside a grid entry."""
    out: List[Tuple[ast.BinOp, List[ast.AST]]] = []

    def walk(node: ast.AST, anc: List[ast.AST]) -> None:
        if isinstance(node, ast.BinOp) and \
                isinstance(node.op, ast.FloorDiv):
            out.append((node, list(anc)))
        for child in ast.iter_child_nodes(node):
            walk(child, anc + [node])

    walk(expr, [])
    return out


def _is_ceil_div(fd: ast.BinOp, ancestors: List[ast.AST]) -> bool:
    # -(-a // b)
    if isinstance(fd.left, ast.UnaryOp) and \
            isinstance(fd.left.op, ast.USub) and any(
                isinstance(a, ast.UnaryOp) and isinstance(a.op, ast.USub)
                for a in ancestors):
        return True
    # (a + b - 1) // b style: compound additive numerator
    if isinstance(fd.left, ast.BinOp) and \
            isinstance(fd.left.op, (ast.Add, ast.Sub)):
        return True
    return False


def _has_divisibility_guard(fi: FunctionInfo, divisor: ast.expr) -> bool:
    want = ast.dump(divisor)
    for node in ast.walk(fi.node):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod) \
                and ast.dump(node.right) == want:
            return True
    return False


def _map_returns(node: ast.AST) -> List[ast.expr]:
    if isinstance(node, ast.Lambda):
        return [node.body]
    out: List[ast.expr] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Return) and sub.value is not None:
            out.append(sub.value)
    return out


def krn003_grid_discipline(fi: FunctionInfo, ctx: KernelContext
                           ) -> List[Finding]:
    sites = _sites_of(ctx, fi)
    if not sites:
        return []
    consts, assigns = _env(ctx, fi)
    out: List[Finding] = []
    for site in sites:
        if site.grid is None:
            continue
        # non-ceil floor division in a grid extent
        for entry in site.grid:
            for fd, anc in _floordivs(entry):
                if _is_ceil_div(fd, anc):
                    continue
                if _has_divisibility_guard(fi, fd.right):
                    continue
                out.append(_finding(
                    fi, fd, "KRN003",
                    "grid extent derived by floor division "
                    f"`{ast.unparse(fd)}` with no ceil-div and no "
                    "divisibility guard in scope — a ragged final tile "
                    "is silently dropped; use pl.cdiv(a, b) (masking "
                    "the tail in-kernel) or guard `a % b == 0`"))
        expected = len(site.grid) + site.num_scalar_prefetch
        for spec in (site.in_specs or []) + (site.out_specs or []):
            if spec.index_map is None:
                continue
            arity = map_arity(fi, spec.index_map, assigns)
            if arity is not None and arity != expected:
                out.append(_finding(
                    fi, spec.index_map, "KRN003",
                    f"index_map takes {arity} args but the site's grid "
                    f"rank + num_scalar_prefetch is {expected} "
                    f"(grid rank {len(site.grid)}, prefetch "
                    f"{site.num_scalar_prefetch}) — Pallas passes one "
                    "arg per grid axis plus one ref per prefetch "
                    "operand; the map silently mis-indexes"))
            # element-offset returns: multiplying by the own block dim
            mapdef = resolve_index_map_def(fi, spec.index_map, assigns)
            if mapdef is None or spec.shape is None:
                continue
            dim_names: Set[str] = set()
            dim_vals: Set[int] = set()
            for d in spec.shape:
                if isinstance(d, ast.Name):
                    dim_names.add(d.id)
                v = eval_dim(d, consts, assigns)
                if v is not None and v > 1:
                    dim_vals.add(v)
            for ret in _map_returns(mapdef):
                elems = ret.elts if isinstance(ret, ast.Tuple) \
                    else [ret]
                for el in elems:
                    for sub in ast.walk(el):
                        if not (isinstance(sub, ast.BinOp) and
                                isinstance(sub.op, ast.Mult)):
                            continue
                        for op in (sub.left, sub.right):
                            hit = (isinstance(op, ast.Name) and
                                   op.id in dim_names) or \
                                  (isinstance(op, ast.Constant) and
                                   op.value in dim_vals)
                            if hit:
                                out.append(_finding(
                                    fi, spec.index_map, "KRN003",
                                    "index_map return multiplies by "
                                    "the spec's own block dimension "
                                    f"(`{ast.unparse(sub)}`) — index "
                                    "maps return BLOCK indices and "
                                    "Pallas scales by the block shape "
                                    "itself; this double-scales the "
                                    "offset"))
                                break
    return out


# ------------------------------------------------------------------ KRN004
def _is_jnp_rooted(fi: FunctionInfo, name: str) -> bool:
    root = name.split(".")[0]
    target = fi.module.module_aliases.get(root, "")
    return target in ("jax.numpy",) or name.startswith(
        ("jnp.", "jax.numpy."))


def _purity_findings(member: FunctionInfo) -> List[Finding]:
    out: List[Finding] = []
    for node in R._body_walk(member):
        if isinstance(node, ast.While):
            out.append(_finding(
                member, node, "KRN004",
                "Python `while` inside a kernel body — Mosaic has no "
                "lowering for data-dependent Python control flow; use "
                "jax.lax.while_loop/fori_loop (or restructure over the "
                "grid)"))
        elif isinstance(node, ast.For):
            it = node.iter
            ok = isinstance(it, (ast.List, ast.Tuple, ast.Constant))
            if isinstance(it, ast.Call):
                tail = (callee_name(it) or "").rsplit(".", 1)[-1]
                ok = tail in _LOOP_ITER_TAILS
            if not ok:
                out.append(_finding(
                    member, node, "KRN004",
                    "Python `for` over a non-static iterable inside a "
                    "kernel body — only range/enumerate/zip over "
                    "Python ints unroll at trace time; iterating a "
                    "traced value needs lax.fori_loop"))
        elif isinstance(node, ast.Call):
            name = callee_name(node)
            if name is None:
                continue
            parts = name.split(".")
            tail = parts[-1]
            root_target = member.module.module_aliases.get(parts[0], "")
            if R._is_numpy_alias(member, parts[0]):
                out.append(_finding(
                    member, node, "KRN004",
                    f"host numpy call {name}(...) inside a kernel "
                    "body — np.* executes at trace time on host "
                    "values; a traced ref here either crashes or "
                    "silently bakes a constant; use jnp"))
            elif root_target.split(".")[0] in _HOST_MODULES or \
                    parts[0] in _HOST_MODULES:
                out.append(_finding(
                    member, node, "KRN004",
                    f"host-module call {name}(...) inside a kernel "
                    "body — clocks/RNG/IO do not exist on the TPU "
                    "core; hoist it out of the kernel"))
            elif tail in _HOST_CALL_TAILS or name.startswith("FLAGS"):
                out.append(_finding(
                    member, node, "KRN004",
                    f"impure call {name}(...) inside a kernel body — "
                    "flags reads, callbacks and debugging hooks have "
                    "no Mosaic lowering; resolve the value at trace "
                    "time and close over it"))
            elif _is_jnp_rooted(member, name) and \
                    tail in _MOSAIC_UNSUPPORTED:
                out.append(_finding(
                    member, node, "KRN004",
                    f"jnp.{tail}(...) has no Mosaic lowering "
                    "(value-dependent shape / unsupported gather) — "
                    "interpret mode runs it, hardware rejects it; "
                    "restructure with masks/top_k-style primitives"))
    return out


def krn004_kernel_purity(fi: FunctionInfo, ctx: KernelContext
                         ) -> List[Finding]:
    if not _kernel_sites(ctx, fi):
        return []
    out: List[Finding] = []
    for member in kernel_closure(ctx.graph, fi):
        out += _purity_findings(member)
    return out


# ------------------------------------------------------------------ KRN005
def _scratch_params(kernel: FunctionInfo, n_scratch: int) -> List[str]:
    node = kernel.node
    if not isinstance(node, ast.FunctionDef) or node.args.vararg:
        return []
    pos = [a.arg for a in node.args.posonlyargs + node.args.args]
    return [p for p in pos[-n_scratch:] if p.endswith("_ref")] \
        if n_scratch and len(pos) >= n_scratch else []


def _stores_to(name: str, node: ast.AST,
               self_ref_only: bool) -> List[ast.AST]:
    out: List[ast.AST] = []
    for sub in ast.walk(node):
        tgt = None
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
            tgt = sub.targets[0]
        elif isinstance(sub, ast.AugAssign):
            tgt = sub.target
        if not (isinstance(tgt, ast.Subscript) and
                isinstance(tgt.value, ast.Name) and
                tgt.value.id == name):
            continue
        if self_ref_only:
            carries = isinstance(sub, ast.AugAssign) or any(
                isinstance(v, ast.Name) and v.id == name
                for v in ast.walk(sub.value))
            if not carries:
                continue
        out.append(sub)
    return out


def _when_decorated(member: FunctionInfo) -> bool:
    node = member.node
    if not isinstance(node, ast.FunctionDef):
        return False
    for dec in node.decorator_list:
        if isinstance(dec, ast.Call) and \
                (callee_name(dec) or "").rsplit(".", 1)[-1] == "when":
            return True
    return False


def krn005_accumulation(fi: FunctionInfo, ctx: KernelContext
                        ) -> List[Finding]:
    out: List[Finding] = []
    # (a) low-precision scratch + carry-init, gated on sites OWNED here
    for site in _sites_of(ctx, fi):
        for sc in site.scratch or []:
            if sc.dtype in _LOW_PRECISION:
                out.append(_finding(
                    fi, site.call, "KRN005",
                    f"{sc.space} scratch declared {sc.dtype} — "
                    "reduction carries accumulate per grid step and "
                    "low-precision carries drift (bf16 has 8 mantissa "
                    "bits); declare scratch f32 and cast on the final "
                    "store"))
        kernel = site.kernel
        if kernel is None or site.scratch is None:
            continue
        closure = kernel_closure(ctx.graph, kernel)
        for pname in _scratch_params(kernel, len(site.scratch)):
            carries = [s for m in closure
                       for s in _stores_to(pname, m.node, True)]
            if not carries:
                continue
            inited = any(
                _when_decorated(m) and _stores_to(pname, m.node, False)
                for m in closure if m is not kernel)
            if not inited:
                out.append(_finding(
                    fi, site.call, "KRN005",
                    f"scratch ref `{pname}` of kernel "
                    f"{kernel.qualname} is carried across grid steps "
                    f"(self-referential store, line "
                    f"{carries[0].lineno}) but never initialized "
                    "under a @pl.when(step == 0) guard — VMEM scratch "
                    "persists across grid cells, so the first "
                    "accumulation reads stale data from the previous "
                    "cell"))
    # (b) unpinned dots, gated on being a kernel of some site
    if _kernel_sites(ctx, fi):
        for member in kernel_closure(ctx.graph, fi):
            for node in R._body_walk(member):
                if isinstance(node, ast.BinOp) and \
                        isinstance(node.op, ast.MatMult):
                    out.append(_finding(
                        member, node, "KRN005",
                        "`@` matmul inside a kernel body cannot pin "
                        "preferred_element_type — on bf16/int8 inputs "
                        "the MXU accumulates at input precision; use "
                        "jax.lax.dot_general(..., "
                        "preferred_element_type=jnp.float32)"))
                elif isinstance(node, ast.Call):
                    tail = (callee_name(node) or "").rsplit(".", 1)[-1]
                    if tail in _DOT_TAILS and not any(
                            kw.arg == "preferred_element_type"
                            for kw in node.keywords):
                        out.append(_finding(
                            member, node, "KRN005",
                            f"{tail}(...) inside a kernel body without "
                            "preferred_element_type — bf16/int8 "
                            "operands accumulate at input precision "
                            "on the MXU; pin "
                            "preferred_element_type=jnp.float32"))
    return out


# ------------------------------------------------------------------ KRN006
def krn006_ref_twin(fi: FunctionInfo, ctx: KernelContext
                    ) -> List[Finding]:
    entries = ctx.uncovered_entries.get(fi.module.relpath)
    if not entries or fi not in entries:
        return []
    return [_finding(
        fi, fi.node, "KRN006",
        f"public pallas entry point {fi.qualname}() has no pure-jnp "
        "twin — the repo's parity convention names it "
        f"{fi.qualname.rsplit('_pallas', 1)[0]}_ref (or _xla/_dense) "
        "so CPU CI can diff kernel output against a reference; "
        "without one, kernel regressions surface only on hardware")]
