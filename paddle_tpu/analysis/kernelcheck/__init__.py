"""kernelcheck — a Pallas/TPU kernel-discipline static analyzer.

tracecheck (r08) gates *trace* discipline, meshcheck (r11) gates
*collective* discipline, faultcheck (r15) gates *recovery* discipline;
kernelcheck gates the TPU kernel invariants the r05–r17 Pallas arc
relies on but can only exercise in CPU interpret mode: tile alignment,
the 16 MB VMEM bound, grid/index-map hygiene, Mosaic-compilable kernel
bodies, f32 accumulation, and the ref-twin parity convention.
Interpret mode cannot manifest any of these failure classes — the lint
checks them statically on every run, off the same shared parse.

Rules (all pure AST over the shared tracecheck parse):

- **KRN001** tile alignment: every statically-provable BlockSpec block
  shape and VMEM scratch shape must have a minor-most dim that is a
  multiple of the 128-lane tile and a second-minor dim aligned to the
  dtype's sublane packing (8/f32, 16/bf16, 32/int8).  Unresolvable
  dims are never findings; SMEM (scalar memory) is exempt.
- **KRN002** static VMEM budget: a site's double-buffered block
  operands plus persistent scratch must fit the 16 MB per-core bound;
  and the fused-decode kernels' extracted ``scratch_shapes`` must
  match the shared templates in ``paddle_tpu.analysis.tile_geometry``
  — the SAME module memwatch's ``plan_fused_layers`` prices from, so
  the planner and the lint cannot disagree.
- **KRN003** grid/index-map discipline: index_map arity must equal
  grid rank + num_scalar_prefetch, grid extents derived by plain floor
  division (no ceil-div, no divisibility guard) drop ragged tails, and
  index maps must return block indices, not element offsets.
- **KRN004** kernel-body purity: no host/numpy/FLAGS/callback/clock
  calls, no Python ``while``/data-dependent iteration, no jnp ops with
  no Mosaic lowering (sort/unique/nonzero/quantile family) anywhere in
  the kernel's same-module call closure.
- **KRN005** accumulation discipline: no bf16/f16 scratch carries,
  every dot in a kernel body pins ``preferred_element_type``, and any
  scratch ref carried across grid steps is initialized under a
  ``@pl.when(step == 0)`` guard.
- **KRN006** ref-twin census: every public pallas entry point has a
  pure-jnp ``<stem>_ref``/``_xla``/``_dense`` twin so CPU CI can diff
  kernel output against a reference.

Findings support inline ``# kernelcheck: disable=KRN00x`` pragmas
(suite-scoped: a tracecheck/meshcheck/faultcheck pragma never silences
KRN rules) and a checked-in baseline (tools/kernelcheck_baseline.json,
kept empty — the r08/r11/r15 precedent is fix, don't baseline); the
tier-1 test gates NEW findings only.

Run it locally::

    python tools/analyze.py                      # all four suites
    python tools/analyze.py --suite kernelcheck
    python tools/analyze.py --changed-only       # git-diff-scoped
    python tools/analyze.py --format sarif       # CI annotation
"""

from ..tracecheck.findings import (Finding, fingerprint, load_baseline,
                                   subtract_baseline, write_baseline)
from .analyzer import AnalyzerConfig, AnalysisResult, analyze_package
from .rules import KERNEL_RULES

__all__ = [
    "AnalyzerConfig", "AnalysisResult", "Finding", "KERNEL_RULES",
    "analyze_package", "fingerprint", "load_baseline",
    "subtract_baseline", "write_baseline",
]
