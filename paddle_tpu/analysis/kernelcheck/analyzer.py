"""Orchestration: parse (or reuse a parse), extract pallas geometry,
run the KRN rules.

``analyze_package`` mirrors the tracecheck/meshcheck/faultcheck entry
points and accepts the same :class:`ParsedPackage`, so the unified CLI
(tools/analyze.py) runs all FOUR suites over ONE ast.parse pass.  The
geometry build is strictly read-only over the shared ``ModuleInfo``
objects — kernelcheck never calls ``propagate_traced`` or mutates
traced/root flags — so running it before or after any other suite
changes nothing about what the others report.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..tracecheck.analyzer import ParsedPackage, parse_package
from ..tracecheck.callgraph import CallGraph
from ..tracecheck.findings import (Finding, dedupe_findings,
                                   parse_pragmas, suppressed)
from .geometry import build_context
from . import rules as KR


@dataclass
class AnalyzerConfig:
    exclude_patterns: tuple = ()
    rules: tuple = ("KRN001", "KRN002", "KRN003", "KRN004", "KRN005",
                    "KRN006")


@dataclass
class AnalysisResult:
    findings: List[Finding]              # post-pragma, pre-baseline
    suppressed: List[Finding]            # pragma-silenced
    n_files: int = 0
    n_functions: int = 0
    n_sites: int = 0                     # pallas_call sites found
    n_specs: int = 0                     # BlockSpec constructors seen
    n_scratch: int = 0                   # VMEM/SMEM allocations seen
    n_kernels: int = 0                   # sites with a resolved kernel
    errors: List[str] = field(default_factory=list)


_RULE_FNS = {
    "KRN001": KR.krn001_tile_alignment,
    "KRN002": KR.krn002_vmem_budget,
    "KRN003": KR.krn003_grid_discipline,
    "KRN004": KR.krn004_kernel_purity,
    "KRN005": KR.krn005_accumulation,
    "KRN006": KR.krn006_ref_twin,
}


def analyze_package(package_path: str,
                    config: Optional[AnalyzerConfig] = None,
                    parsed: Optional[ParsedPackage] = None
                    ) -> AnalysisResult:
    config = config or AnalyzerConfig()
    if parsed is None:
        parsed = parse_package(package_path, config.exclude_patterns)
    else:
        parsed = parsed.filtered(config.exclude_patterns)

    result = AnalysisResult(findings=[], suppressed=[])
    result.errors = list(parsed.errors)
    result.n_files = parsed.n_files

    graph = CallGraph(parsed.modules, parsed.package)
    ctx = build_context(parsed.modules, graph)
    result.n_sites = ctx.n_sites
    result.n_specs = ctx.n_specs
    result.n_scratch = ctx.n_scratch
    result.n_kernels = ctx.n_kernels

    findings: List[Finding] = []
    for mod in parsed.modules.values():
        pragmas = parse_pragmas(mod.source_lines, tool="kernelcheck")
        for fi in mod.functions.values():
            result.n_functions += 1
            batch: List[Finding] = []
            for code in config.rules:
                fn = _RULE_FNS.get(code)
                if fn is not None:
                    batch += fn(fi, ctx)
            for f in batch:
                (result.suppressed if suppressed(f, pragmas)
                 else findings).append(f)

    result.findings = dedupe_findings(findings)
    return result
