"""Pallas-site geometry extraction (pure AST, read-only).

Walks every function of the shared tracecheck parse and recovers, for
each ``pl.pallas_call`` site, the static geometry the KRN rules check:
the grid, the BlockSpec block shapes and index maps (chased through
local list variables, ``+=``/``.append()`` building, ``[spec] * 2``
replication, conditional branches, and append-helper nested defs), the
``pltpu.VMEM``/``SMEM`` scratch shapes and dtypes, the scalar-prefetch
count, and the kernel body (resolved through ``functools.partial`` and
local-name indirection).

Everything here is a *read* of the shared ``ModuleInfo`` objects — no
traced/root flags are touched, so running kernelcheck before or after
the other suites changes nothing (the order-independence contract of
tools/analyze.py).

Shapes stay **AST expressions**: a dimension like ``tr_h`` or
``nh * d`` is only resolved to an integer when a constant environment
(module constants, literal local assigns, ``tile()`` calls) can prove
its value — rules make no claim about dimensions they cannot prove.
"""

from __future__ import annotations

import ast
import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..tracecheck.callgraph import (CallGraph, FunctionInfo, ModuleInfo,
                                    _dotted, callee_name)
from ..tile_geometry import tile

__all__ = [
    "KernelContext", "PallasSite", "ScratchInfo", "SpecInfo",
    "build_context", "eval_dim", "kernel_closure", "map_arity",
]


@dataclass
class SpecInfo:
    """One ``pl.BlockSpec`` (an in/out block operand)."""
    role: str                              # "in" | "out" | "unknown"
    shape: Optional[Tuple[ast.expr, ...]]  # None = non-literal shape
    index_map: Optional[ast.expr]          # second arg / index_map kwarg
    lineno: int = 0


@dataclass
class ScratchInfo:
    """One ``pltpu.VMEM``/``pltpu.SMEM`` scratch allocation."""
    space: str                             # "VMEM" | "SMEM"
    shape: Optional[Tuple[ast.expr, ...]]
    dtype: str                             # dtype tail name ('' unknown)
    lineno: int = 0


@dataclass
class PallasSite:
    """One ``pl.pallas_call`` with whatever geometry resolved."""
    fi: FunctionInfo
    call: ast.Call
    lineno: int
    kernel: Optional[FunctionInfo] = None
    grid: Optional[Tuple[ast.expr, ...]] = None
    num_scalar_prefetch: int = 0
    in_specs: Optional[List[SpecInfo]] = None
    out_specs: Optional[List[SpecInfo]] = None
    scratch: Optional[List[ScratchInfo]] = None
    specs_complete: bool = False           # every spec list fully chased


@dataclass
class KernelContext:
    graph: CallGraph
    modules: Dict[str, ModuleInfo]
    sites: Dict[str, List[PallasSite]] = field(default_factory=dict)
    # fi.qualname (per module) -> constructor census
    census_specs: Dict[Tuple[str, str], List[SpecInfo]] = \
        field(default_factory=dict)
    census_scratch: Dict[Tuple[str, str], List[ScratchInfo]] = \
        field(default_factory=dict)
    # module relpath -> uncovered public pallas entry FunctionInfos
    uncovered_entries: Dict[str, List[FunctionInfo]] = \
        field(default_factory=dict)
    # per-module int-constant env cache (filled lazily by the rules)
    mod_consts: Dict[str, Dict[str, int]] = field(default_factory=dict)
    n_sites: int = 0
    n_specs: int = 0
    n_scratch: int = 0
    n_kernels: int = 0


# ------------------------------------------------------------ utilities
def _tail(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def _own_statements(node: ast.AST):
    """Iterate the statements of a function body WITHOUT descending into
    nested function/lambda scopes (their assignments are not ours)."""
    stack = list(getattr(node, "body", []))
    while stack:
        stmt = stack.pop(0)
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for fld in ("body", "orelse", "finalbody"):
            stack.extend(getattr(stmt, fld, []) or [])
        for h in getattr(stmt, "handlers", []) or []:
            stack.extend(h.body)


def _scalar_assigns(fi: FunctionInfo) -> Dict[str, List[ast.expr]]:
    """name -> every ``name = <expr>`` value assigned in fi's own body
    (both branches of conditionals contribute)."""
    out: Dict[str, List[ast.expr]] = {}
    for stmt in _own_statements(fi.node):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            out.setdefault(stmt.targets[0].id, []).append(stmt.value)
    return out


def _module_consts(mod: ModuleInfo) -> Dict[str, int]:
    consts: Dict[str, int] = {}
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Constant) and \
                isinstance(stmt.value.value, int):
            consts[stmt.targets[0].id] = stmt.value.value
    # `from ...tile_geometry import LANES [as X]` — the one cross-module
    # constant worth knowing (the lane tile itself)
    for local, (modpath, orig) in mod.imported_names.items():
        if orig == "LANES" and modpath.endswith("tile_geometry"):
            consts[local] = 128
    return consts


def eval_dim(expr: ast.expr, consts: Dict[str, int],
             assigns: Optional[Dict[str, List[ast.expr]]] = None,
             _depth: int = 0) -> Optional[int]:
    """Best-effort integer evaluation of a shape dimension.  Returns
    None for anything not statically provable (runtime shapes, function
    parameters, tuple unpacks)."""
    if _depth > 8:
        return None
    if isinstance(expr, ast.Constant):
        return expr.value if isinstance(expr.value, int) and \
            not isinstance(expr.value, bool) else None
    if isinstance(expr, ast.Name):
        if expr.id in consts:
            return consts[expr.id]
        vals = (assigns or {}).get(expr.id, [])
        if len(vals) == 1:
            return eval_dim(vals[0], consts, assigns, _depth + 1)
        return None
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        v = eval_dim(expr.operand, consts, assigns, _depth + 1)
        return -v if v is not None else None
    if isinstance(expr, ast.BinOp):
        a = eval_dim(expr.left, consts, assigns, _depth + 1)
        b = eval_dim(expr.right, consts, assigns, _depth + 1)
        if a is None or b is None:
            return None
        try:
            if isinstance(expr.op, ast.Add):
                return a + b
            if isinstance(expr.op, ast.Sub):
                return a - b
            if isinstance(expr.op, ast.Mult):
                return a * b
            if isinstance(expr.op, ast.FloorDiv):
                return a // b
            if isinstance(expr.op, ast.Mod):
                return a % b
            if isinstance(expr.op, ast.Pow):
                return a ** b
        except (ZeroDivisionError, OverflowError, ValueError):
            return None
        return None
    if isinstance(expr, ast.Call):
        tail = _tail(callee_name(expr))
        args = [eval_dim(a, consts, assigns, _depth + 1)
                for a in expr.args]
        if any(a is None for a in args):
            return None
        if tail in ("tile", "_tile") and len(args) == 2:
            return tile(args[0], args[1])
        if tail == "max" and args:
            return max(args)
        if tail == "min" and args:
            return min(args)
    return None


# ------------------------------------------------------- list building
class _ParamSub(ast.NodeTransformer):
    def __init__(self, mapping: Dict[str, ast.expr]):
        self.mapping = mapping

    def visit_Name(self, node: ast.Name):
        if node.id in self.mapping:
            return copy.deepcopy(self.mapping[node.id])
        return node


def _resolve_list_expr(expr: ast.expr,
                       lists: Dict[str, List[ast.expr]]
                       ) -> Optional[List[ast.expr]]:
    if isinstance(expr, (ast.List, ast.Tuple)):
        return list(expr.elts)
    if isinstance(expr, ast.Name):
        got = lists.get(expr.id)
        return list(got) if got is not None else None
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        a = _resolve_list_expr(expr.left, lists)
        b = _resolve_list_expr(expr.right, lists)
        return a + b if a is not None and b is not None else None
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mult):
        base, n = expr.left, expr.right
        if isinstance(base, ast.Constant):
            base, n = expr.right, expr.left
        elems = _resolve_list_expr(base, lists)
        if elems is not None and isinstance(n, ast.Constant) and \
                isinstance(n.value, int):
            return elems * max(n.value, 1)
        return None
    if isinstance(expr, ast.IfExp):
        a = _resolve_list_expr(expr.body, lists)
        b = _resolve_list_expr(expr.orelse, lists)
        if a is None and b is None:
            return None
        return (a or []) + (b or [])
    return None


def _collect_lists(fi: FunctionInfo, mod: ModuleInfo
                   ) -> Tuple[Dict[str, List[ast.expr]], set]:
    """Statement-ordered chase of list variables in fi's own body.
    Returns (name -> element exprs, names whose chase was inexact —
    rebound to something unresolvable, or extended in a loop we only
    walked once)."""
    lists: Dict[str, List[ast.expr]] = {}
    inexact: set = set()

    def helper_appends(call: ast.Call) -> bool:
        """``_weight(w, spec, imap)``-style append helpers: a nested def
        of fi whose body appends (substituted) exprs to our lists."""
        name = callee_name(call)
        if name is None or "." in name:
            return False
        helper = mod.functions.get(fi.qualname + "." + name)
        if helper is None or not isinstance(helper.node, ast.FunctionDef):
            return False
        params = [a.arg for a in helper.node.args.args]
        if len(call.args) > len(params) or call.keywords:
            return False
        mapping = dict(zip(params, call.args))
        sub = _ParamSub(mapping)
        did = False
        for stmt in _own_statements(helper.node):
            if isinstance(stmt, ast.Expr) and \
                    isinstance(stmt.value, ast.Call) and \
                    isinstance(stmt.value.func, ast.Attribute) and \
                    stmt.value.func.attr == "append" and \
                    isinstance(stmt.value.func.value, ast.Name) and \
                    stmt.value.func.value.id in lists and \
                    len(stmt.value.args) == 1:
                lists[stmt.value.func.value.id].append(
                    sub.visit(copy.deepcopy(stmt.value.args[0])))
                did = True
        return did

    def walk(body, in_loop: bool):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                n = stmt.targets[0].id
                r = _resolve_list_expr(stmt.value, lists)
                if r is not None:
                    lists[n] = r
                    if in_loop:
                        inexact.add(n)
                elif n in lists:
                    del lists[n]
                    inexact.add(n)
            elif isinstance(stmt, ast.AugAssign) and \
                    isinstance(stmt.target, ast.Name) and \
                    isinstance(stmt.op, ast.Add) and \
                    stmt.target.id in lists:
                r = _resolve_list_expr(stmt.value, lists)
                if r is not None:
                    lists[stmt.target.id].extend(r)
                else:
                    inexact.add(stmt.target.id)
                if in_loop:
                    inexact.add(stmt.target.id)
            elif isinstance(stmt, ast.Expr) and \
                    isinstance(stmt.value, ast.Call):
                call = stmt.value
                if isinstance(call.func, ast.Attribute) and \
                        call.func.attr == "append" and \
                        isinstance(call.func.value, ast.Name) and \
                        call.func.value.id in lists and \
                        len(call.args) == 1:
                    lists[call.func.value.id].append(call.args[0])
                    if in_loop:
                        inexact.add(call.func.value.id)
                else:
                    helper_appends(call)
            elif isinstance(stmt, (ast.For, ast.While)):
                walk(stmt.body, True)
                walk(stmt.orelse, True)
            elif isinstance(stmt, ast.If):
                walk(stmt.body, in_loop)
                walk(stmt.orelse, in_loop)
            elif isinstance(stmt, (ast.With, ast.Try)):
                walk(getattr(stmt, "body", []), in_loop)
                for h in getattr(stmt, "handlers", []) or []:
                    walk(h.body, in_loop)
                walk(getattr(stmt, "orelse", []) or [], in_loop)
                walk(getattr(stmt, "finalbody", []) or [], in_loop)

    walk(fi.node.body if hasattr(fi.node, "body") else [], False)
    return lists, inexact


# ----------------------------------------------------- spec construction
def _as_specs(elems: List[ast.expr], role: str,
              assigns: Dict[str, List[ast.expr]]) -> List[SpecInfo]:
    """BlockSpec constructor exprs (or Names resolving to them) ->
    SpecInfo list; unrecognized elements contribute a shapeless spec so
    counts stay honest."""
    out: List[SpecInfo] = []
    for e in elems:
        cands = [e]
        if isinstance(e, ast.Name):
            cands = assigns.get(e.id, [])
        made = False
        for c in cands:
            s = _spec_from_call(c, role)
            if s is not None:
                out.append(s)
                made = True
        if not made:
            out.append(SpecInfo(role=role, shape=None, index_map=None,
                                lineno=getattr(e, "lineno", 0)))
    return out


def _spec_from_call(expr: ast.expr, role: str) -> Optional[SpecInfo]:
    if not isinstance(expr, ast.Call) or \
            _tail(callee_name(expr)) != "BlockSpec":
        return None
    shape_arg = expr.args[0] if expr.args else None
    index_map = expr.args[1] if len(expr.args) > 1 else None
    for kw in expr.keywords:
        if kw.arg == "block_shape":
            shape_arg = kw.value
        elif kw.arg == "index_map":
            index_map = kw.value
    shape = tuple(shape_arg.elts) \
        if isinstance(shape_arg, (ast.Tuple, ast.List)) else None
    return SpecInfo(role=role, shape=shape, index_map=index_map,
                    lineno=expr.lineno)


def _scratch_from_call(expr: ast.expr) -> Optional[ScratchInfo]:
    if not isinstance(expr, ast.Call):
        return None
    tail = _tail(callee_name(expr))
    if tail not in ("VMEM", "SMEM"):
        return None
    shape_arg = expr.args[0] if expr.args else None
    shape = tuple(shape_arg.elts) \
        if isinstance(shape_arg, (ast.Tuple, ast.List)) else None
    dtype = ""
    if len(expr.args) > 1:
        dtype = _tail(_dotted(expr.args[1]) or "")
    return ScratchInfo(space=tail, shape=shape, dtype=dtype,
                       lineno=expr.lineno)


def _as_scratch(elems: List[ast.expr],
                assigns: Dict[str, List[ast.expr]]) -> List[ScratchInfo]:
    out: List[ScratchInfo] = []
    for e in elems:
        cands = [e]
        if isinstance(e, ast.Name):
            cands = assigns.get(e.id, [])
        made = False
        for c in cands:
            s = _scratch_from_call(c)
            if s is not None:
                out.append(s)
                made = True
        if not made:
            out.append(ScratchInfo(space="VMEM", shape=None, dtype="",
                                   lineno=getattr(e, "lineno", 0)))
    return out


# ----------------------------------------------------- kernel resolution
def _local_named(mod: ModuleInfo, fi: FunctionInfo, name: str
                 ) -> Optional[FunctionInfo]:
    scope: Optional[FunctionInfo] = fi
    while scope is not None:
        hit = mod.functions.get(scope.qualname + "." + name)
        if hit is not None:
            return hit
        scope = scope.parent
    return mod.functions.get(name)


def _resolve_kernel(fi: FunctionInfo, expr: ast.expr,
                    assigns: Dict[str, List[ast.expr]],
                    _depth: int = 0) -> Optional[FunctionInfo]:
    if _depth > 4 or expr is None:
        return None
    mod = fi.module
    if isinstance(expr, ast.Lambda):
        for f in mod.functions.values():
            if isinstance(f.node, ast.Lambda) and \
                    f.node.lineno == expr.lineno and \
                    f.node.col_offset == expr.col_offset:
                return f
        return None
    if isinstance(expr, ast.Name):
        hit = _local_named(mod, fi, expr.id)
        if hit is not None and not isinstance(hit.node, ast.Lambda):
            return hit
        for v in assigns.get(expr.id, []):
            got = _resolve_kernel(fi, v, assigns, _depth + 1)
            if got is not None:
                return got
        return hit
    if isinstance(expr, ast.Call):
        name = callee_name(expr)
        if name and _tail(name) == "partial" and expr.args:
            return _resolve_kernel(fi, expr.args[0], assigns, _depth + 1)
        if name is not None and "." not in name:
            return _local_named(mod, fi, name)
    return None


def kernel_closure(graph: CallGraph, kernel: FunctionInfo
                   ) -> List[FunctionInfo]:
    """The kernel body plus its same-module helpers: lexically nested
    defs and statically resolvable same-module callees (transitively).
    This is what KRN004/KRN005 walk."""
    mod = kernel.module
    seen: Dict[str, FunctionInfo] = {}
    work = [kernel]
    while work:
        fi = work.pop()
        if fi.qualname in seen:
            continue
        seen[fi.qualname] = fi
        prefix = fi.qualname + "."
        for qn, nested in mod.functions.items():
            if qn.startswith(prefix) and qn not in seen:
                work.append(nested)
        for call in fi.calls:
            for callee in graph.resolve_call(fi, call):
                if callee.module is mod and callee.qualname not in seen:
                    work.append(callee)
    return list(seen.values())


def map_arity(site_fi: FunctionInfo, expr: Optional[ast.expr],
              assigns: Dict[str, List[ast.expr]],
              _depth: int = 0) -> Optional[int]:
    """Positional arity of an index map: lambda, local/module def,
    or a factory call returning a nested def.  None = cannot prove
    (varargs, unresolvable)."""
    if expr is None or _depth > 4:
        return None
    mod = site_fi.module

    def _args_of(node) -> Optional[int]:
        a = node.args
        if a.vararg is not None:
            return None
        return len(a.posonlyargs) + len(a.args)

    if isinstance(expr, ast.Lambda):
        return _args_of(expr)
    if isinstance(expr, ast.Name):
        fn = _local_named(mod, site_fi, expr.id)
        if fn is not None and isinstance(
                fn.node, (ast.FunctionDef, ast.Lambda)):
            return _args_of(fn.node)
        for v in assigns.get(expr.id, []):
            got = map_arity(site_fi, v, assigns, _depth + 1)
            if got is not None:
                return got
        return None
    if isinstance(expr, ast.Call):
        # factory: _phase_map(off, steps, nr) returning a nested def
        name = callee_name(expr)
        if name is None or "." in name:
            return None
        factory = _local_named(mod, site_fi, name)
        if factory is None or not isinstance(factory.node,
                                             ast.FunctionDef):
            return None
        for stmt in _own_statements(factory.node):
            if isinstance(stmt, ast.Return):
                if isinstance(stmt.value, ast.Lambda):
                    return _args_of(stmt.value)
                if isinstance(stmt.value, ast.Name):
                    inner = mod.functions.get(
                        factory.qualname + "." + stmt.value.id)
                    if inner is not None and isinstance(
                            inner.node, ast.FunctionDef):
                        return _args_of(inner.node)
        return None
    return None


def resolve_index_map_def(site_fi: FunctionInfo,
                          expr: Optional[ast.expr],
                          assigns: Dict[str, List[ast.expr]]
                          ) -> Optional[ast.AST]:
    """The def/lambda node behind an index-map expr (for return-value
    inspection), following the same paths as :func:`map_arity`."""
    if expr is None:
        return None
    mod = site_fi.module
    if isinstance(expr, ast.Lambda):
        return expr
    if isinstance(expr, ast.Name):
        fn = _local_named(mod, site_fi, expr.id)
        if fn is not None and isinstance(
                fn.node, (ast.FunctionDef, ast.Lambda)):
            return fn.node
        for v in assigns.get(expr.id, []):
            got = resolve_index_map_def(site_fi, v, assigns)
            if got is not None:
                return got
        return None
    if isinstance(expr, ast.Call):
        name = callee_name(expr)
        if name is None or "." in name:
            return None
        factory = _local_named(mod, site_fi, name)
        if factory is None or not isinstance(factory.node,
                                             ast.FunctionDef):
            return None
        for stmt in _own_statements(factory.node):
            if isinstance(stmt, ast.Return):
                if isinstance(stmt.value, ast.Lambda):
                    return stmt.value
                if isinstance(stmt.value, ast.Name):
                    inner = mod.functions.get(
                        factory.qualname + "." + stmt.value.id)
                    if inner is not None:
                        return inner.node
        return None
    return None


# --------------------------------------------------------- site parsing
def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _resolve_tuple(expr: Optional[ast.expr],
                   assigns: Dict[str, List[ast.expr]]
                   ) -> Optional[Tuple[ast.expr, ...]]:
    if expr is None:
        return None
    if isinstance(expr, (ast.Tuple, ast.List)):
        return tuple(expr.elts)
    if isinstance(expr, ast.Name):
        vals = assigns.get(expr.id, [])
        if len(vals) == 1:
            return _resolve_tuple(vals[0], assigns)
    return None


def _extract_site(fi: FunctionInfo, call: ast.Call, graph: CallGraph
                  ) -> PallasSite:
    mod = fi.module
    assigns = _scalar_assigns(fi)
    lists, inexact = _collect_lists(fi, mod)
    site = PallasSite(fi=fi, call=call, lineno=call.lineno)
    site.kernel = _resolve_kernel(
        fi, call.args[0] if call.args else None, assigns)

    # locate the grid-spec call: grid_spec= kwarg (inline or via a local
    # name), else the pallas_call itself carries grid/in_specs/...
    spec_call: Optional[ast.Call] = None
    gs = _kwarg(call, "grid_spec")
    if isinstance(gs, ast.Name):
        for v in assigns.get(gs.id, []):
            if isinstance(v, ast.Call):
                gs = v
                break
    if isinstance(gs, ast.Call) and _tail(callee_name(gs)) in (
            "PrefetchScalarGridSpec", "GridSpec"):
        spec_call = gs
    carrier = spec_call if spec_call is not None else call

    site.grid = _resolve_tuple(_kwarg(carrier, "grid"), assigns)
    nsp = _kwarg(carrier, "num_scalar_prefetch")
    if isinstance(nsp, ast.Constant) and isinstance(nsp.value, int):
        site.num_scalar_prefetch = nsp.value

    complete = True
    for role, kw in (("in", "in_specs"), ("out", "out_specs")):
        raw = _kwarg(carrier, kw)
        if raw is None:
            continue
        elems = _resolve_list_expr(raw, lists)
        if elems is None:
            # a single BlockSpec (out_specs commonly) or a lone Name
            single = _spec_from_call(raw, role)
            if single is None and isinstance(raw, ast.Name):
                if raw.id in inexact:
                    complete = False
                for v in assigns.get(raw.id, []):
                    single = single or _spec_from_call(v, role)
            if single is not None:
                elems = [raw]
            else:
                complete = False
        if isinstance(raw, ast.Name) and raw.id in inexact:
            complete = False
        if elems is not None:
            specs = _as_specs(elems, role, assigns)
            if role == "in":
                site.in_specs = specs
            else:
                site.out_specs = specs
        else:
            complete = False
    raw = _kwarg(carrier, "scratch_shapes")
    if raw is not None:
        elems = _resolve_list_expr(raw, lists)
        if isinstance(raw, ast.Name) and raw.id in inexact:
            complete = False
        if elems is not None:
            site.scratch = _as_scratch(elems, assigns)
        else:
            complete = False
    site.specs_complete = complete and site.grid is not None
    return site


# ----------------------------------------------------------- module census
_REF_SUFFIXES = ("_ref", "_xla", "_dense")


def _entry_stem(name: str) -> str:
    return name[:-len("_pallas")] if name.endswith("_pallas") else name


def _uncovered_entries(mod: ModuleInfo, graph: CallGraph,
                       has_site: set) -> List[FunctionInfo]:
    """Public top-level functions that (transitively, within the module)
    reach a pallas_call but have no ``<stem>_ref/_xla/_dense`` twin."""
    # transitive reach, within-module resolution only
    reaches = set(has_site)
    changed = True
    while changed:
        changed = False
        for qn, fi in mod.functions.items():
            if qn in reaches or not qn:
                continue
            for call in fi.calls:
                if any(c.module is mod and c.qualname in reaches
                       for c in graph.resolve_call(fi, call)):
                    reaches.add(qn)
                    changed = True
                    break
    ref_stems = [n[:-len(s)] for n in mod.functions
                 for s in _REF_SUFFIXES
                 if "." not in n and n.endswith(s)]
    out: List[FunctionInfo] = []
    for qn in sorted(reaches):
        fi = mod.functions.get(qn)
        if fi is None or "." in qn or qn.startswith("_") or \
                fi.cls is not None:
            continue
        stem = _entry_stem(qn)
        if not any(stem.startswith(rs) or rs.startswith(stem)
                   for rs in ref_stems):
            out.append(fi)
    return out


# --------------------------------------------------------------- context
def build_context(modules: Dict[str, ModuleInfo],
                  graph: CallGraph) -> KernelContext:
    ctx = KernelContext(graph=graph, modules=modules)
    for mod in modules.values():
        mp = mod.relpath          # rules look functions up by relpath
        has_site: set = set()
        for qn, fi in mod.functions.items():
            specs: List[SpecInfo] = []
            scratch: List[ScratchInfo] = []
            for call in fi.calls:
                tail = _tail(callee_name(call))
                if tail == "BlockSpec":
                    s = _spec_from_call(call, "unknown")
                    if s is not None:
                        specs.append(s)
                elif tail in ("VMEM", "SMEM"):
                    s = _scratch_from_call(call)
                    if s is not None:
                        scratch.append(s)
                elif tail == "pallas_call":
                    site = _extract_site(fi, call, graph)
                    ctx.sites.setdefault(mp, []).append(site)
                    has_site.add(qn)
                    ctx.n_sites += 1
                    if site.kernel is not None:
                        ctx.n_kernels += 1
            if specs:
                ctx.census_specs[(mp, qn)] = specs
                ctx.n_specs += len(specs)
            if scratch:
                ctx.census_scratch[(mp, qn)] = scratch
                ctx.n_scratch += len(scratch)
        if has_site:
            unc = _uncovered_entries(mod, graph, has_site)
            if unc:
                ctx.uncovered_entries[mod.relpath] = unc
    return ctx
