"""Static-analysis tooling that ships with the framework.

The reference stack pairs its kernels with correctness tooling
(FLAGS_check_nan_inf sanitizer layers, op-level debugging hooks); this
package holds the *static* half: analyzers that catch trace-discipline
and SPMD collective-discipline bugs at lint time instead of on-chip.
See :mod:`.tracecheck` (TRC rules) and :mod:`.meshcheck` (MSH rules);
``tools/analyze.py`` runs both over one shared parse.
"""
