"""Static-analysis tooling that ships with the framework.

The reference stack pairs its kernels with correctness tooling
(FLAGS_check_nan_inf sanitizer layers, op-level debugging hooks); this
package holds the *static* half: analyzers that catch trace-discipline,
SPMD collective-discipline, recovery-discipline, and TPU
kernel-discipline bugs at lint time instead of on-chip (or at drill
time).  See :mod:`.tracecheck` (TRC rules), :mod:`.meshcheck` (MSH
rules), :mod:`.faultcheck` (FLT rules), and :mod:`.kernelcheck` (KRN
rules); ``tools/analyze.py`` runs all four over one shared parse.

:mod:`.tile_geometry` is the jax-free TPU tile/VMEM geometry module
shared by the fused-decode kernel, the memwatch planner, and
kernelcheck's KRN002 budget — one source for block shapes so the
planner and the lint can never disagree.
"""
