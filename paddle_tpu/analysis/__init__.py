"""Static-analysis tooling that ships with the framework.

The reference stack pairs its kernels with correctness tooling
(FLAGS_check_nan_inf sanitizer layers, op-level debugging hooks); this
package holds the *static* half: analyzers that catch trace-discipline,
SPMD collective-discipline, and recovery-discipline bugs at lint time
instead of on-chip (or at drill time).  See :mod:`.tracecheck` (TRC
rules), :mod:`.meshcheck` (MSH rules), and :mod:`.faultcheck` (FLT
rules); ``tools/analyze.py`` runs all three over one shared parse.
"""
