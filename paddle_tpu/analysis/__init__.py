"""Static-analysis tooling that ships with the framework.

The reference stack pairs its kernels with correctness tooling
(FLAGS_check_nan_inf sanitizer layers, op-level debugging hooks); this
package holds the *static* half: analyzers that catch trace-discipline,
SPMD collective-discipline, recovery-discipline, TPU
kernel-discipline, host-state handoff-discipline, and compiled-program
identity bugs at lint time instead of on-chip (or at drill time, on
the far side of a process boundary, or as a stale cached program in
production).  See :mod:`.tracecheck` (TRC rules), :mod:`.meshcheck`
(MSH rules), :mod:`.faultcheck` (FLT rules), :mod:`.kernelcheck` (KRN
rules), :mod:`.statecheck` (STC rules), and :mod:`.keycheck` (KEY
rules); ``tools/analyze.py`` runs all six over one shared parse.

:mod:`.tile_geometry` is the jax-free TPU tile/VMEM geometry module
shared by the fused-decode kernel, the memwatch planner, and
kernelcheck's KRN002 budget — one source for block shapes so the
planner and the lint can never disagree.  :mod:`.key_vocab` plays the
same role for program identity: the ``DecodeKey.extra`` tag grammar
that ``generation/serving.py`` mints keys with and keycheck's KEY006
lints against — identical-by-object, no drift possible.
"""
