"""The program-identity model keycheck reasons over (pure AST, shared
parse).

Four questions drive the KEY rules:

1. **Which flags ride programs?**  ``PROGRAM_FLAGS`` as the analyzed
   package declares it — read from ``flags.py`` by AST at analysis time
   (the meshcheck ``_HYBRID_AXES`` idiom), with
   :data:`..key_vocab.PROGRAM_FLAGS_FALLBACK` as the fixture-package
   safety net — plus the discriminant flags whose values ride the key
   as components (``serving_kv_dtype`` -> ``("kv", dtype)``).

2. **Where are keys minted?**  Every ``DecodeKey(...)`` construction.
   A construction whose ``kind`` is a parameter makes the enclosing
   function a *minter* (``ServingEngine._key``); its call sites are
   then resolved through the call graph and each becomes an effective
   key site with the caller's kind/extra arguments bound to the
   minter's parameters.  ``extra``-tuple reassignment chains in the
   minter body (``extra = tuple(extra) + (("kv", ...),)``) contribute
   the appended grammar.

3. **What guards admission?**  Every
   ``decode_program_cache().get(key, builder)`` call, with the builder
   resolved through names, locals, ``functools.partial`` and lambdas
   (the r15 donors.py return-of-local lesson).  The transitive closure
   of functions reachable from builder bodies is the set whose flag
   reads KEY001 audits.

4. **What may ``extra`` say?**  The tag/atom vocabulary from the
   analyzed package's ``analysis/key_vocab.py`` (again by AST, so
   fixture packages can declare their own), falling back to the
   constants this suite itself imports — identical-by-object with what
   ``generation/serving.py`` uses at runtime.

Everything here is READ-ONLY over the shared :class:`ModuleInfo`
objects, so running keycheck never changes what the other suites
report on the same parse, in either order.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..tracecheck.callgraph import (CallGraph, FunctionInfo, ModuleInfo,
                                    _dotted, callee_name)
from ..tracecheck.rules import _body_walk
from .. import key_vocab

# ------------------------------------------------- vocabulary extraction

def _module_str_symbols(tree: ast.Module) -> Dict[str, str]:
    """NAME = "literal" assignments at module scope (TAG_KV = "kv")."""
    syms: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            syms[node.targets[0].id] = node.value.value
    return syms


def _assigned_value(tree: ast.Module, name: str) -> Optional[ast.expr]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == name:
            return node.value
    return None


def _const_str_set(tree: ast.Module, syms: Dict[str, str],
                   name: str) -> Optional[frozenset]:
    """Resolve ``NAME = frozenset({...})`` / tuple / list of string
    constants (or of names bound to string constants)."""
    val = _assigned_value(tree, name)
    if val is None:
        return None
    if isinstance(val, ast.Call) and val.args:
        val = val.args[0]
    if not isinstance(val, (ast.Tuple, ast.List, ast.Set)):
        return None
    out: Set[str] = set()
    for el in val.elts:
        if isinstance(el, ast.Constant) and isinstance(el.value, str):
            out.add(el.value)
        elif isinstance(el, ast.Name) and el.id in syms:
            out.add(syms[el.id])
    return frozenset(out)


def _const_dict_keys(tree: ast.Module, syms: Dict[str, str],
                     name: str) -> Optional[frozenset]:
    val = _assigned_value(tree, name)
    if not isinstance(val, ast.Dict):
        return None
    out: Set[str] = set()
    for k in val.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            out.add(k.value)
        elif isinstance(k, ast.Name) and k.id in syms:
            out.add(syms[k.id])
    return frozenset(out)


def _find_module(modules: Dict[str, ModuleInfo],
                 *suffixes: str) -> Optional[ModuleInfo]:
    hits = [m for m in modules.values()
            if any(m.relpath.endswith(s) for s in suffixes)]
    if not hits:
        return None
    # prefer the shallowest path (the package's own top-level flags.py
    # over some vendored copy)
    return min(hits, key=lambda m: (m.relpath.count("/"), m.relpath))


def program_flags_vocabulary(modules: Dict[str, ModuleInfo]) -> frozenset:
    """``PROGRAM_FLAGS`` as declared by the analyzed package's
    ``flags.py``, else the key_vocab fallback (fixture packages)."""
    mod = _find_module(modules, "/flags.py", "flags.py")
    if mod is not None:
        vocab = _const_str_set(mod.tree, {}, "PROGRAM_FLAGS")
        if vocab:
            return vocab
    return key_vocab.PROGRAM_FLAGS_FALLBACK


def declared_flag_names(modules: Dict[str, ModuleInfo]
                        ) -> Optional[frozenset]:
    """Every ``define_flag("name", ...)`` in the analyzed package's
    flags module; None when the package has no flags.py (fixtures) —
    callers then treat every candidate name as a flag."""
    mod = _find_module(modules, "/flags.py", "flags.py")
    if mod is None:
        return None
    names: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            cn = (callee_name(node) or "").rsplit(".", 1)[-1]
            if cn == "define_flag" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                names.add(node.args[0].value)
    return frozenset(names) if names else None


@dataclass
class ExtraVocabulary:
    tags: frozenset
    atoms: frozenset
    discriminants: frozenset          # flag names riding key components
    derived_attrs: frozenset          # KEY002 closure allowlist
    snapshot_attrs: frozenset
    symbols: Dict[str, str]           # vocab constant name -> tag string
    source: str                       # relpath of the vocab module, or ""


def extra_vocabulary(modules: Dict[str, ModuleInfo]) -> ExtraVocabulary:
    """The tag/atom vocabulary from the analyzed package's
    ``analysis/key_vocab.py`` (AST — fixture packages can declare their
    own), falling back to the constants this suite imports itself."""
    mod = _find_module(modules, "analysis/key_vocab.py", "key_vocab.py")
    if mod is not None:
        syms = _module_str_symbols(mod.tree)
        tags = _const_str_set(mod.tree, syms, "EXTRA_TAGS")
        atoms = _const_str_set(mod.tree, syms, "EXTRA_ATOMS")
        if tags is not None or atoms is not None:
            return ExtraVocabulary(
                tags=tags or frozenset(),
                atoms=atoms or frozenset(),
                discriminants=_const_dict_keys(
                    mod.tree, syms, "DISCRIMINANT_FLAGS") or frozenset(),
                derived_attrs=_const_str_set(
                    mod.tree, syms, "KEY_DERIVED_ATTRS") or frozenset(),
                snapshot_attrs=_const_str_set(
                    mod.tree, syms, "SNAPSHOT_ATTRS")
                or frozenset(key_vocab.SNAPSHOT_ATTRS),
                symbols=syms, source=mod.relpath)
    syms = {n: v for n, v in vars(key_vocab).items()
            if n.isupper() and isinstance(v, str)}
    return ExtraVocabulary(
        tags=key_vocab.EXTRA_TAGS, atoms=key_vocab.EXTRA_ATOMS,
        discriminants=frozenset(key_vocab.DISCRIMINANT_FLAGS),
        derived_attrs=key_vocab.KEY_DERIVED_ATTRS,
        snapshot_attrs=key_vocab.SNAPSHOT_ATTRS,
        symbols=syms, source="")


# --------------------------------------------------------- model objects

@dataclass
class KeySite:
    """One effective DecodeKey minting site: either a direct
    ``DecodeKey(...)`` construction with a statically-known kind, or a
    resolved call into a minter with the caller's arguments bound."""
    fi: FunctionInfo
    node: ast.Call
    kinds: Tuple[str, ...]            # () when the kind is opaque
    via: Optional[str]                # minter qualname for call sites
    fields: List[Tuple[str, ast.expr]]
    grammar: Optional[Tuple[str, ...]]  # extra schema; None = opaque
    unregistered: List[Tuple[ast.AST, str]] = field(default_factory=list)


@dataclass
class Minter:
    """A function that constructs DecodeKey from its own parameters
    (``ServingEngine._key`` / ``_spec_program``)."""
    fi: FunctionInfo
    key_node: ast.Call
    params: List[str]                 # declared order, self/cls dropped
    defaults: Dict[str, ast.expr]
    kind_param: Optional[str]
    extra_param: Optional[str]
    appended: Tuple[str, ...] = ()    # grammar appended in the body
    appended_unregistered: List[Tuple[ast.AST, str]] = \
        field(default_factory=list)


@dataclass
class Admission:
    """One ``decode_program_cache().get(key, builder)`` call."""
    fi: FunctionInfo
    node: ast.Call
    builder_expr: ast.expr
    builder_fis: List[FunctionInfo]
    binds: List[Tuple[str, ast.expr]]  # partial-bound (name, value expr)


@dataclass
class SetSite:
    """One ``flags.set_flags({...})`` / registry ``.set("name", v)``."""
    fi: FunctionInfo
    node: ast.Call
    names: Tuple[str, ...]            # statically-known flag names


@dataclass
class KeyContext:
    graph: CallGraph
    program_flags: frozenset
    flag_names: Optional[frozenset]
    vocab: ExtraVocabulary
    key_sites: List[KeySite] = field(default_factory=list)
    minters: Dict[int, Minter] = field(default_factory=dict)
    admissions: List[Admission] = field(default_factory=list)
    builder_reachable: Set[int] = field(default_factory=set)
    snapshot_sites: List[Tuple[FunctionInfo, ast.Call]] = \
        field(default_factory=list)
    set_sites: List[SetSite] = field(default_factory=list)
    schema_conflicts: List[Tuple[KeySite, str, Tuple, Tuple, KeySite]] = \
        field(default_factory=list)
    observed_tags: Set[str] = field(default_factory=set)
    observed_atoms: Set[str] = field(default_factory=set)

    @property
    def discriminants(self) -> frozenset:
        return self.vocab.discriminants


def _tail(name: Optional[str]) -> str:
    return (name or "").rsplit(".", 1)[-1]


# ------------------------------------------------------ local resolution

def _local_assigns(fi: FunctionInfo, name: str) -> List[ast.expr]:
    """Every statically-visible ``name = <expr>`` in this function
    (pruned walk: a closure's assigns belong to its own FunctionInfo).
    All arms matter — the decode builder local is assigned once per
    if/elif dispatch arm."""
    found: List[ast.expr] = []
    for node in _body_walk(fi):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == name:
            found.append(node.value)
    return found


def _local_assign(fi: FunctionInfo, name: str) -> Optional[ast.expr]:
    found = _local_assigns(fi, name)
    return found[0] if found else None


def _kind_strings(fi: FunctionInfo, expr: Optional[ast.expr],
                  depth: int = 0) -> Tuple[str, ...]:
    """Statically-known kind strings an expression can evaluate to
    (constants, locals, conditional expressions — the fused/nlayer
    kind pivot is an IfExp of two constants)."""
    if expr is None or depth > 4:
        return ()
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return (expr.value,)
    if isinstance(expr, ast.IfExp):
        return (_kind_strings(fi, expr.body, depth + 1)
                + _kind_strings(fi, expr.orelse, depth + 1))
    if isinstance(expr, ast.Name):
        return _kind_strings(fi, _local_assign(fi, expr.id), depth + 1)
    return ()


def _resolve_str(expr: ast.expr, symbols: Dict[str, str]
                 ) -> Optional[str]:
    """A string the expression statically names: a constant, a vocab
    constant by Name, or ``key_vocab.TAG_X`` by Attribute."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.Name):
        return symbols.get(expr.id)
    if isinstance(expr, ast.Attribute):
        return symbols.get(expr.attr)
    return None


def _grammar_of(fi: FunctionInfo, expr: Optional[ast.expr],
                ctx: KeyContext, depth: int = 0
                ) -> Tuple[Optional[Tuple[str, ...]],
                           List[Tuple[ast.AST, str]]]:
    """(schema descriptor, unregistered strings) for an extra
    expression.  None schema = opaque (a parameter, an unresolvable
    name) — opaque sites make no KEY006 schema claim but still get
    their statically-visible strings vocabulary-checked."""
    unreg: List[Tuple[ast.AST, str]] = []
    if expr is None:
        return (), unreg
    if depth > 6:
        return None, unreg
    syms = ctx.vocab.symbols

    if isinstance(expr, ast.Tuple):
        gram: List[str] = []
        for el in expr.elts:
            s = _resolve_str(el, syms)
            if s is not None:
                if s in ctx.vocab.tags:
                    ctx.observed_tags.add(s)
                    gram.append(f"tag:{s}")
                elif s in ctx.vocab.atoms:
                    ctx.observed_atoms.add(s)
                    gram.append(f"atom:{s}")
                else:
                    unreg.append((el, s))
                    gram.append(f"?:{s}")
            elif isinstance(el, ast.Tuple) and el.elts:
                head = _resolve_str(el.elts[0], syms)
                if head is not None:
                    if head in ctx.vocab.tags:
                        ctx.observed_tags.add(head)
                        gram.append(f"pair:{head}")
                    else:
                        unreg.append((el.elts[0], head))
                        gram.append(f"pair:?{head}")
                else:
                    gram.append("pair")
            elif isinstance(el, ast.Constant) and \
                    isinstance(el.value, int):
                gram.append("int")
            elif isinstance(el, ast.Dict):
                gram.append("dict")     # KEY003's finding, not KEY006's
            elif isinstance(el, ast.Call):
                gram.append("seq" if _tail(callee_name(el)) == "tuple"
                            else "expr")
            else:
                gram.append("var")
        return tuple(gram), unreg

    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        lg, lu = _grammar_of(fi, expr.left, ctx, depth + 1)
        rg, ru = _grammar_of(fi, expr.right, ctx, depth + 1)
        unreg = lu + ru
        if lg is None or rg is None:
            return None, unreg
        return lg + rg, unreg

    if isinstance(expr, ast.IfExp):
        # both arms contribute to the vocabulary check; the schema
        # itself becomes an alternative (opaque for conflict purposes)
        _, bu = _grammar_of(fi, expr.body, ctx, depth + 1)
        _, ou = _grammar_of(fi, expr.orelse, ctx, depth + 1)
        return None, bu + ou

    if isinstance(expr, ast.Name):
        local = _local_assign(fi, expr.id)
        if local is not None:
            return _grammar_of(fi, local, ctx, depth + 1)
        return None, unreg

    if isinstance(expr, ast.Call) and _tail(callee_name(expr)) == "tuple":
        return None, unreg
    return None, unreg


# -------------------------------------------------------- site scanning

_KEY_FIELDS = ("kind", "model_sig", "batch_bucket", "page_budget",
               "dtype", "flags", "extra")


def _call_fields(node: ast.Call,
                 param_names: Tuple[str, ...]) -> List[Tuple[str,
                                                             ast.expr]]:
    fields: List[Tuple[str, ast.expr]] = []
    for i, a in enumerate(node.args):
        fields.append((param_names[i] if i < len(param_names)
                       else f"arg{i}", a))
    for kw in node.keywords:
        if kw.arg is not None:
            fields.append((kw.arg, kw.value))
    return fields


def _field_expr(fields: List[Tuple[str, ast.expr]],
                name: str) -> Optional[ast.expr]:
    for n, e in fields:
        if n == name:
            return e
    return None


def _fn_params(fi: FunctionInfo) -> Tuple[List[str], Dict[str, ast.expr]]:
    """Declared parameter names (self/cls dropped) and their defaults."""
    if isinstance(fi.node, ast.Lambda):
        args = fi.node.args
    elif isinstance(fi.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        args = fi.node.args
    else:
        return [], {}
    names = [a.arg for a in args.args]
    if fi.cls and names and names[0] in ("self", "cls"):
        names = names[1:]
    defaults: Dict[str, ast.expr] = {}
    pos = args.args[-len(args.defaults):] if args.defaults else []
    for a, d in zip(pos, args.defaults):
        defaults[a.arg] = d
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None:
            defaults[a.arg] = d
    names += [a.arg for a in args.kwonlyargs if a.arg not in names]
    return names, defaults


def _minter_appends(minter: Minter, ctx: KeyContext) -> None:
    """Grammar appended to the extra parameter inside the minter body:
    ``extra = tuple(extra) + (("kv", ...),) [+ (("tp", N),)]``."""
    if minter.extra_param is None:
        return
    gram: List[str] = []
    for node in _body_walk(minter.fi):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == minter.extra_param):
            continue
        val = node.value
        while isinstance(val, ast.BinOp) and isinstance(val.op, ast.Add):
            g, u = _grammar_of(minter.fi, val.right, ctx)
            if g is not None:
                gram = list(g) + gram
            minter.appended_unregistered.extend(u)
            val = val.left
    minter.appended = tuple(gram)


def _scan_decode_keys(fi: FunctionInfo, ctx: KeyContext) -> None:
    for node in _body_walk(fi):
        if not isinstance(node, ast.Call):
            continue
        if _tail(callee_name(node)) != "DecodeKey":
            continue
        fields = _call_fields(node, _KEY_FIELDS)
        kind_expr = _field_expr(fields, "kind")
        params, defaults = _fn_params(fi)
        if isinstance(kind_expr, ast.Name) and kind_expr.id in params \
                and _local_assign(fi, kind_expr.id) is None:
            # kind comes from a parameter: this function is a minter
            extra_expr = _field_expr(fields, "extra")
            extra_param = (extra_expr.id
                           if isinstance(extra_expr, ast.Name)
                           and extra_expr.id in params else None)
            minter = Minter(fi=fi, key_node=node, params=params,
                            defaults=defaults,
                            kind_param=kind_expr.id,
                            extra_param=extra_param)
            _minter_appends(minter, ctx)
            ctx.minters[id(fi)] = minter
            # the construction itself stays a (kind-opaque) site so
            # KEY003/KEY004 audit its direct field expressions
            ctx.key_sites.append(KeySite(
                fi=fi, node=node, kinds=(), via=None, fields=fields,
                grammar=None))
            continue
        kinds = _kind_strings(fi, kind_expr)
        gram, unreg = _grammar_of(fi, _field_expr(fields, "extra"), ctx)
        ctx.key_sites.append(KeySite(
            fi=fi, node=node, kinds=kinds, via=None, fields=fields,
            grammar=gram, unregistered=unreg))


def _scan_minter_calls(fi: FunctionInfo, ctx: KeyContext) -> None:
    for call in fi.calls:
        for target in ctx.graph.resolve_call(fi, call):
            minter = ctx.minters.get(id(target))
            if minter is None or target is fi:
                continue
            fields = _call_fields(call, tuple(minter.params))
            kinds = _kind_strings(
                fi, _field_expr(fields, minter.kind_param or "kind"))
            extra_expr = _field_expr(fields, minter.extra_param
                                     or "extra")
            if extra_expr is None and minter.extra_param:
                extra_expr = minter.defaults.get(minter.extra_param)
            gram, unreg = _grammar_of(fi, extra_expr, ctx)
            ctx.key_sites.append(KeySite(
                fi=fi, node=call, kinds=kinds, via=target.qualname,
                fields=fields, grammar=gram, unregistered=unreg))


# ----------------------------------------------------- admission scanning

def _cache_get_call(fi: FunctionInfo, node: ast.Call) -> bool:
    """True for ``<decode_program_cache()>.get(key, builder, ...)`` —
    directly chained or through a local bound to the cache."""
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr == "get" and len(node.args) >= 2):
        return False
    base = node.func.value
    if isinstance(base, ast.Call):
        return _tail(callee_name(base)) == "decode_program_cache"
    if isinstance(base, ast.Name):
        local = _local_assign(fi, base.id)
        return isinstance(local, ast.Call) and \
            _tail(callee_name(local)) == "decode_program_cache"
    return False


def _lookup_function(fi: FunctionInfo, name: str
                     ) -> Optional[FunctionInfo]:
    """Resolve a bare name to a FunctionInfo: enclosing-scope nested
    defs first, then module-level defs (the donors.py scope chain)."""
    mod = fi.module
    scope: Optional[FunctionInfo] = fi
    while scope is not None:
        hit = mod.functions.get(
            (scope.qualname + "." if scope.qualname else "") + name)
        if hit is not None:
            return hit
        scope = scope.parent
    return mod.functions.get(name)


def _resolve_builder(fi: FunctionInfo, expr: ast.expr, ctx: KeyContext,
                     depth: int = 0
                     ) -> Tuple[List[FunctionInfo],
                                List[Tuple[str, ast.expr]]]:
    """(builder FunctionInfos, partial-bound (name, value) pairs) for a
    builder expression — through names, locals assigned earlier,
    ``functools.partial`` and lambdas (the r15 return-of-local lesson)."""
    if depth > 4:
        return [], []
    if isinstance(expr, ast.Lambda):
        fis = [f for f in fi.module.functions.values()
               if f.node is expr]
        return fis, []
    if isinstance(expr, ast.Name):
        hit = _lookup_function(fi, expr.id)
        if hit is not None:
            return [hit], []
        fis: List[FunctionInfo] = []
        binds: List[Tuple[str, ast.expr]] = []
        for local in _local_assigns(fi, expr.id):
            lf, lb = _resolve_builder(fi, local, ctx, depth + 1)
            fis.extend(f for f in lf if f not in fis)
            binds.extend(lb)
        return fis, binds
    if isinstance(expr, ast.Attribute):
        chain = _dotted(expr)
        if chain:
            parts = chain.split(".")
            if parts[0] in ("self", "cls") and len(parts) == 2 and fi.cls:
                hit = fi.module.functions.get(f"{fi.cls}.{parts[1]}")
                return ([hit], []) if hit else ([], [])
        return [], []
    if isinstance(expr, ast.Call):
        if _tail(callee_name(expr)) == "partial" and expr.args:
            fis, _ = _resolve_builder(fi, expr.args[0], ctx, depth + 1)
            binds: List[Tuple[str, ast.expr]] = []
            pnames: List[str] = []
            if fis:
                pnames, _d = _fn_params(fis[0])
            for i, a in enumerate(expr.args[1:]):
                binds.append((pnames[i] if i < len(pnames)
                              else f"arg{i}", a))
            for kw in expr.keywords:
                if kw.arg is not None:
                    binds.append((kw.arg, kw.value))
            return fis, binds
        # builder() call result admitted directly — not the contract,
        # leave opaque
        return [], []
    return [], []


def _scan_admissions(fi: FunctionInfo, ctx: KeyContext) -> None:
    for node in _body_walk(fi):
        if isinstance(node, ast.Call) and _cache_get_call(fi, node):
            builder_expr = node.args[1]
            fis, binds = _resolve_builder(fi, builder_expr, ctx)
            ctx.admissions.append(Admission(
                fi=fi, node=node, builder_expr=builder_expr,
                builder_fis=fis, binds=binds))


def _forwarded_admissions(ctx: KeyContext,
                          modules: Dict[str, ModuleInfo]) -> None:
    """An admission whose builder is a *parameter* of the admitting
    function (``_spec_program(kind, extra, builder)``) is opaque at
    the ``.get`` — the partial is built by the caller.  Audit every
    resolved call site that supplies the parameter, so KEY002 sees the
    caller's binds and the builder lands in the reachable set."""
    forwarding: Dict[int, Tuple[FunctionInfo, str]] = {}
    for adm in ctx.admissions:
        be = adm.builder_expr
        params, _ = _fn_params(adm.fi)
        if isinstance(be, ast.Name) and be.id in params and \
                not _local_assigns(adm.fi, be.id):
            forwarding[id(adm.fi)] = (adm.fi, be.id)
    if not forwarding:
        return
    extra: List[Admission] = []
    for mod in modules.values():
        for fi in mod.functions.values():
            for call in fi.calls:
                for target in ctx.graph.resolve_call(fi, call):
                    fwd = forwarding.get(id(target))
                    if fwd is None or target is fi:
                        continue
                    tparams, _d = _fn_params(target)
                    fields = _call_fields(call, tuple(tparams))
                    bexpr = _field_expr(fields, fwd[1])
                    if bexpr is None:
                        continue
                    fis, binds = _resolve_builder(fi, bexpr, ctx)
                    extra.append(Admission(
                        fi=fi, node=call, builder_expr=bexpr,
                        builder_fis=fis, binds=binds))
    ctx.admissions.extend(extra)


# ------------------------------------------------- flag mutation / reads

def _scan_flag_calls(fi: FunctionInfo, ctx: KeyContext) -> None:
    for node in _body_walk(fi):
        if not isinstance(node, ast.Call):
            continue
        name = callee_name(node) or ""
        tail = _tail(name)
        root = name.split(".")[0]
        if tail == "snapshot" and ("flags" in root
                                   or root in ("self", "cls")):
            ctx.snapshot_sites.append((fi, node))
        elif tail == "set_flags" and node.args and \
                isinstance(node.args[0], ast.Dict):
            names = tuple(k.value for k in node.args[0].keys
                          if isinstance(k, ast.Constant)
                          and isinstance(k.value, str))
            if names:
                ctx.set_sites.append(SetSite(fi, node, names))
        elif tail == "set" and node.args and \
                ("flags" in root or "registry" in root.lstrip("_")) and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            ctx.set_sites.append(
                SetSite(fi, node, (node.args[0].value,)))


# ------------------------------------------------------- reachable build

def _builder_reachable(ctx: KeyContext,
                       modules: Dict[str, ModuleInfo]) -> None:
    seeds: List[FunctionInfo] = []
    for adm in ctx.admissions:
        seeds.extend(adm.builder_fis)
    seen: Set[int] = set()
    frontier = list(seeds)
    while frontier:
        fi = frontier.pop()
        if id(fi) in seen:
            continue
        seen.add(id(fi))
        prefix = fi.qualname + "."
        for other in fi.module.functions.values():
            if other.qualname.startswith(prefix) and \
                    id(other) not in seen:
                frontier.append(other)
        for call in fi.calls:
            for target in ctx.graph.resolve_call(fi, call):
                if id(target) not in seen:
                    frontier.append(target)
    ctx.builder_reachable = seen


# ------------------------------------------------------------- assembly

def build_context(modules: Dict[str, ModuleInfo],
                  graph: CallGraph) -> KeyContext:
    ctx = KeyContext(graph=graph,
                     program_flags=program_flags_vocabulary(modules),
                     flag_names=declared_flag_names(modules),
                     vocab=extra_vocabulary(modules))
    fis = [f for m in sorted(modules.values(),
                             key=lambda m: m.relpath)
           for f in m.functions.values()]
    for fi in fis:                       # pass 1: minters + direct sites
        _scan_decode_keys(fi, ctx)
    for fi in fis:                       # pass 2: minter call sites
        _scan_minter_calls(fi, ctx)
    for fi in fis:
        _scan_admissions(fi, ctx)
        _scan_flag_calls(fi, ctx)
    _forwarded_admissions(ctx, modules)
    _builder_reachable(ctx, modules)

    # one kind = one extra schema, package-wide (KEY006)
    schemas: Dict[str, Tuple[Tuple[str, ...], KeySite]] = {}
    for site in ctx.key_sites:
        if site.grammar is None or not site.kinds:
            continue
        for kind in site.kinds:
            prior = schemas.get(kind)
            if prior is None:
                schemas[kind] = (site.grammar, site)
            elif prior[0] != site.grammar:
                ctx.schema_conflicts.append(
                    (site, kind, site.grammar, prior[0], prior[1]))
    return ctx
