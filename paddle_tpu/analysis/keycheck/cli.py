"""keycheck CLI (single-suite; tools/analyze.py runs all six suites
over one parse).

Exit codes: 0 clean (or all findings baselined/suppressed), 1 new
findings, 2 usage/parse errors.  ``--json`` includes the key census
(decode_key_sites, kinds, extra_tags, builders, snapshot_sites).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from ..tracecheck.findings import (load_baseline, subtract_baseline,
                                   write_baseline)
from .analyzer import AnalyzerConfig, analyze_package
from .rules import KEY_RULES

DEFAULT_BASELINE = os.path.join("tools", "keycheck_baseline.json")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="keycheck",
        description="Compiled-program identity & cache-key soundness "
                    "analyzer (KEY001-006).")
    p.add_argument("path", nargs="?", default="paddle_tpu",
                   help="package directory (or single file) to analyze")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings + key census as JSON on stdout")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE} "
                        "next to the analyzed package when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: report every finding")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of rules to run")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--stats", action="store_true",
                   help="print file/function/key-census counters")
    return p


def _default_baseline_path(pkg_path: str) -> str:
    parent = os.path.dirname(os.path.abspath(pkg_path.rstrip(os.sep)))
    return os.path.join(parent, DEFAULT_BASELINE)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for code in sorted(KEY_RULES):
            print(f"{code}: {KEY_RULES[code]}")
        return 0
    if not os.path.exists(args.path):
        print(f"keycheck: no such path: {args.path}", file=sys.stderr)
        return 2

    config = AnalyzerConfig()
    if args.rules:
        if args.update_baseline:
            # a rule-filtered run sees a subset of findings — writing
            # it out would erase every unselected rule's baseline
            # entries (the r11 hardening parity rule)
            print("keycheck: --rules cannot be combined with "
                  "--update-baseline (it would clobber the other "
                  "rules' baseline entries)", file=sys.stderr)
            return 2
        config = AnalyzerConfig(
            rules=tuple(r.strip().upper() for r in args.rules.split(",")
                        if r.strip()))

    t0 = time.time()
    result = analyze_package(args.path, config)
    elapsed = time.time() - t0
    for err in result.errors:
        print(f"keycheck: parse error: {err}", file=sys.stderr)
    if result.errors:
        return 2

    baseline_path = args.baseline or _default_baseline_path(args.path)
    if args.update_baseline:
        entries = write_baseline(baseline_path, result.findings)
        print(f"keycheck: baselined {len(entries)} finding(s) -> "
              f"{baseline_path}")
        return 0

    baseline = (load_baseline(baseline_path) if not args.no_baseline
                else None)
    if baseline:
        new, leftovers = subtract_baseline(result.findings, baseline)
        n_baselined = len(result.findings) - len(new)
    else:
        new, leftovers, n_baselined = result.findings, {}, 0

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "baselined": n_baselined,
            "suppressed": len(result.suppressed),
            "stale_baseline_entries": sorted(leftovers),
            "files": result.n_files,
            "functions": result.n_functions,
            "key_sites": result.n_key_sites,
            "kinds": result.n_kinds,
            "extra_tags": result.n_tags,
            "builders": result.n_builders,
            "admissions": result.n_admissions,
            "minters": result.n_minters,
            "census": result.census,
            "elapsed_s": round(elapsed, 3),
        }, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.format())
        if args.stats:
            print(f"-- {result.n_files} files, {result.n_functions} "
                  f"functions ({result.n_key_sites} key sites / "
                  f"{result.n_kinds} kinds / {result.n_tags} tags, "
                  f"{result.n_builders} builders in "
                  f"{result.n_admissions} admissions, "
                  f"{result.n_minters} minters) in {elapsed:.2f}s")
        summary = (f"keycheck: {len(new)} new finding(s), "
                   f"{n_baselined} baselined, "
                   f"{len(result.suppressed)} pragma-suppressed")
        if leftovers:
            summary += (f"; {sum(leftovers.values())} stale baseline "
                        "entr(ies) — run --update-baseline")
        print(summary)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
