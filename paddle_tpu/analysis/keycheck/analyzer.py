"""Orchestration: parse (or reuse a parse), build the program-identity
model, run the KEY rules.

``analyze_package`` mirrors the other suites' entry points and accepts
the same :class:`ParsedPackage`, so the unified CLI (tools/analyze.py)
runs all SIX suites over ONE ast.parse pass.  The context build is
read-only over the shared ``ModuleInfo`` objects, so running keycheck
never changes what the other suites report on the same parse, in
either order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..tracecheck.analyzer import ParsedPackage, parse_package
from ..tracecheck.callgraph import CallGraph
from ..tracecheck.findings import (Finding, dedupe_findings,
                                   parse_pragmas, suppressed)
from .key_model import build_context
from . import rules as KR


@dataclass
class AnalyzerConfig:
    exclude_patterns: tuple = ()
    rules: tuple = ("KEY001", "KEY002", "KEY003", "KEY004", "KEY005",
                    "KEY006")


@dataclass
class AnalysisResult:
    findings: List[Finding]              # post-pragma, pre-baseline
    suppressed: List[Finding]            # pragma-silenced
    n_files: int = 0
    n_functions: int = 0
    n_key_sites: int = 0                 # kind-resolved DecodeKey sites
    n_kinds: int = 0                     # distinct program kinds
    n_tags: int = 0                      # extra tags observed in use
    n_builders: int = 0                  # resolved builder functions
    n_admissions: int = 0                # cache .get(key, builder) calls
    n_minters: int = 0                   # DecodeKey-from-params functions
    census: Dict[str, object] = field(default_factory=dict)
    errors: List[str] = field(default_factory=list)


_RULE_FNS = {
    "KEY001": KR.key001_untracked_flag_read,
    "KEY002": KR.key002_builder_closure,
    "KEY003": KR.key003_component_hygiene,
    "KEY004": KR.key004_per_dispatch_value,
    "KEY005": KR.key005_invalidation_discipline,
    "KEY006": KR.key006_extra_grammar,
}


def analyze_package(package_path: str,
                    config: Optional[AnalyzerConfig] = None,
                    parsed: Optional[ParsedPackage] = None
                    ) -> AnalysisResult:
    config = config or AnalyzerConfig()
    if parsed is None:
        parsed = parse_package(package_path, config.exclude_patterns)
    else:
        parsed = parsed.filtered(config.exclude_patterns)

    result = AnalysisResult(findings=[], suppressed=[])
    result.errors = list(parsed.errors)
    result.n_files = parsed.n_files

    graph = CallGraph(parsed.modules, parsed.package)
    ctx = build_context(parsed.modules, graph)

    sites = [s for s in ctx.key_sites if s.kinds]
    kinds = sorted({k for s in sites for k in s.kinds})
    builders = sorted({bfi.qualname for adm in ctx.admissions
                       for bfi in adm.builder_fis})
    result.n_key_sites = len(sites)
    result.n_kinds = len(kinds)
    result.n_tags = len(ctx.observed_tags)
    result.n_builders = len(builders)
    result.n_admissions = len(ctx.admissions)
    result.n_minters = len(ctx.minters)
    result.census = {
        "decode_key_sites": sorted(
            f"{s.fi.module.relpath}:{s.node.lineno} "
            f"kind={'|'.join(s.kinds)}"
            + (f" via={s.via}" if s.via else "") for s in sites),
        "kinds": kinds,
        "extra_tags": sorted(ctx.observed_tags),
        "extra_atoms": sorted(ctx.observed_atoms),
        "builders": builders,
        "minters": sorted(m.fi.qualname for m in ctx.minters.values()),
        "snapshot_sites": sorted(
            f"{fi.module.relpath}:{node.lineno}"
            for fi, node in ctx.snapshot_sites),
        "set_sites": sorted(
            f"{s.fi.module.relpath}:{s.node.lineno} "
            f"{','.join(s.names)}" for s in ctx.set_sites),
        "program_flags": sorted(ctx.program_flags),
        "vocab_source": ctx.vocab.source,
    }

    findings: List[Finding] = []
    for mod in parsed.modules.values():
        pragmas = parse_pragmas(mod.source_lines, tool="keycheck")
        for fi in mod.functions.values():
            result.n_functions += 1
            batch: List[Finding] = []
            for code in config.rules:
                fn = _RULE_FNS.get(code)
                if fn is not None:
                    batch += fn(fi, ctx)
            for f in batch:
                (result.suppressed if suppressed(f, pragmas)
                 else findings).append(f)

    result.findings = dedupe_findings(findings)
    return result
