"""keycheck — a compiled-program identity & cache-key soundness
analyzer.

tracecheck (r08) gates *trace* discipline, meshcheck (r11)
*collective* discipline, faultcheck (r15) *recovery* discipline,
kernelcheck (r20) *kernel* discipline, and statecheck (r21) *handoff*
discipline; keycheck gates the contract all of serving rides on:
``DecodeKey`` IS a compiled program's identity.  The two silent
failure classes — a key-relevant input left OUT of the key (a stale
program serves wrong math forever) and a per-dispatch value left IN
(unbounded retrace churn on the most expensive compiles in the repo)
— are invisible to the dynamic zero-retrace probes, which only see
config combinations a test actually exercised.  Key soundness is a
static property — check it before the collision ships.

Rules (all pure AST over the shared tracecheck parse):

- **KEY001** flag read reachable from a cached builder's traced body
  where the flag is neither in ``PROGRAM_FLAGS`` (read from
  ``flags.py`` by AST at analysis time) nor a key discriminant —
  ``serving_kv_dtype`` is the annotated exemplar: eager-only BY
  DESIGN because the dtype rides ``DecodeKey.extra``.
- **KEY002** builder closure over mutable engine state not derivable
  from key components (the documented generic/prefill model-object
  closure is the pragma'd exemplar) — a second engine sharing the
  key must get identical math.
- **KEY003** key-component hygiene: unhashable/identity-hashed
  objects, device values, raw floats, dicts in key fields or
  ``extra``.
- **KEY004** per-dispatch-varying values keyed — step counters, live
  queue lengths, clocks/rng: retrace churn made static.
- **KEY005** cache-invalidation discipline: a ``PROGRAM_FLAGS``
  member mutated on a path that neither routes through
  ``clear_decode_program_cache()`` nor mints a new key.
- **KEY006** ``extra``-grammar discipline: one kind = one extra
  schema package-wide, tag vocabulary registered in the jax-free
  :mod:`..key_vocab` that ``generation/serving.py`` imports back
  (identical-by-object — the tile_geometry/bundle_vocab coupling
  pattern), so tree-spec and LoRA keys register tags instead of
  inventing colliding positional tuples.

The dynamic twin (tests/test_key_matrix.py) instantiates engines
across the config lattice and proves the other direction at runtime:
distinct configs mint distinct keys, identical configs share
programs, eager-only flag toggles change NO key, and every
``PROGRAM_FLAGS`` toggle changes ALL decode keys.

Findings support inline ``# keycheck: disable=KEY00x`` pragmas
(suite-scoped: another suite's pragma never silences KEY rules) and a
checked-in baseline (tools/keycheck_baseline.json, kept empty — the
precedent is fix, don't baseline); the tier-1 test gates NEW findings
only.

Run it locally::

    python tools/analyze.py                   # all six suites
    python tools/analyze.py --suite keycheck
    python tools/keycheck.py --json           # key census included
"""

from ..tracecheck.findings import (Finding, fingerprint, load_baseline,
                                   subtract_baseline, write_baseline)
from .analyzer import AnalyzerConfig, AnalysisResult, analyze_package
from .key_model import (declared_flag_names, extra_vocabulary,
                        program_flags_vocabulary)
from .rules import KEY_RULES

__all__ = [
    "AnalyzerConfig", "AnalysisResult", "Finding", "KEY_RULES",
    "analyze_package", "declared_flag_names", "extra_vocabulary",
    "fingerprint", "load_baseline", "program_flags_vocabulary",
    "subtract_baseline", "write_baseline",
]
