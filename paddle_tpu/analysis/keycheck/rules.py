"""The six KEY rules (compiled-program identity & cache-key soundness).

Each rule is ``fn(fi, ctx) -> List[Finding]`` over the program-identity
model in :mod:`.key_model`; all state is precomputed there, so the
rules are pure filters and the suite stays READ-ONLY over the shared
parse.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from ..tracecheck.callgraph import FunctionInfo, _dotted, callee_name
from ..tracecheck.findings import Finding
from ..tracecheck.rules import _body_walk
from ..statecheck.bundle_vocab import device_producing
from .key_model import Admission, KeyContext, KeySite

KEY_RULES = {
    "KEY001": "flag read reachable from a cached builder's traced body "
              "where the flag is neither in PROGRAM_FLAGS nor a DecodeKey "
              "discriminant — the compiled program freezes whatever value "
              "it saw at trace time and serves it forever (stale-program "
              "class; eager-only flags must stay out of traced bodies, or "
              "ride the key like serving_kv_dtype does).",
    "KEY002": "cached-program builder closes over mutable engine state "
              "that is not derivable from the key's components — a second "
              "engine admitted under the same key silently gets the FIRST "
              "engine's math (the documented generic/prefill model-object "
              "closure is the pragma'd exemplar).",
    "KEY003": "key-component hygiene: unhashable or identity-hashed "
              "object, device value, or raw float in a DecodeKey field "
              "or extra tuple — keys must be pure host tuples with value "
              "semantics (dict/list/set literals, floats, id()/hash(), "
              "jnp-produced values).",
    "KEY004": "per-dispatch-varying value keyed (step counter, live "
              "queue/batch length, clock or rng) — every dispatch mints "
              "a fresh key, so the program cache retraces forever "
              "(retrace churn made static; key the bucket/rung, not the "
              "live value).",
    "KEY005": "PROGRAM_FLAGS member mutated on a path that neither "
              "routes through clear_decode_program_cache() nor mints a "
              "new key — cached programs keep their old flag tuple's "
              "fault-site binding and memwatch banking until re-armed "
              "(program_cache.py's documented re-arm contract).",
    "KEY006": "extra-grammar discipline: a tag/atom not registered in "
              "analysis/key_vocab.py, or a second extra schema for a "
              "kind that already has one — one kind = one extra schema "
              "package-wide, so new key families (tree-spec, LoRA) "
              "cannot collide with existing positional tuples.",
}


def _finding(fi: FunctionInfo, node: ast.AST, rule: str,
             msg: str) -> Finding:
    line = getattr(node, "lineno", fi.lineno)
    return Finding(rule=rule, path=fi.module.relpath, line=line,
                   func=fi.qualname, message=msg,
                   source=fi.module.line(line))


def _tail(name: Optional[str]) -> str:
    return (name or "").rsplit(".", 1)[-1]


def _sites_of(fi: FunctionInfo, ctx: KeyContext) -> Iterator[KeySite]:
    for site in ctx.key_sites:
        if site.fi is fi:
            yield site


# ------------------------------------------------------------- KEY001

_SNAP_PARAM_NAMES = frozenset({"snap", "snapshot"})


def _snapshot_names(fi: FunctionInfo) -> frozenset:
    """Names bound to a flag snapshot and visible in this scope:
    parameters named like one, and locals assigned from a
    ``*.snapshot(...)`` call — in this function or any lexically
    enclosing one (a nested traced body reads the builder's snap)."""
    names = set()
    cur: Optional[FunctionInfo] = fi
    while cur is not None:
        node = cur.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for a in list(node.args.args) + list(node.args.kwonlyargs):
                if a.arg in _SNAP_PARAM_NAMES or a.arg.endswith("_snap"):
                    names.add(a.arg)
        for sub in _body_walk(cur):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call) \
                    and _tail(callee_name(sub.value)) == "snapshot":
                names.add(sub.targets[0].id)
        cur = cur.parent
    return frozenset(names)


def key001_untracked_flag_read(fi: FunctionInfo,
                               ctx: KeyContext) -> List[Finding]:
    if id(fi) not in ctx.builder_reachable:
        return []
    out: List[Finding] = []
    tracked = ctx.program_flags | ctx.discriminants
    snap_names = _snapshot_names(fi)

    def is_flag(name: str) -> bool:
        return ctx.flag_names is None or name in ctx.flag_names

    for node in _body_walk(fi):
        if isinstance(node, ast.Call):
            if _tail(callee_name(node)) == "get_flag" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                name = node.args[0].value
                if name not in tracked and is_flag(name):
                    out.append(_finding(
                        fi, node, "KEY001",
                        f"get_flag('{name}') is reachable from a cached "
                        "builder but the flag is not in PROGRAM_FLAGS "
                        "(nor a key discriminant) — the compiled program "
                        "freezes the trace-time value"))
        elif isinstance(node, ast.Attribute):
            base = _dotted(node.value)
            if base is None:
                continue
            parts = base.split(".")
            is_snap = (len(parts) == 1 and parts[0] in snap_names) or \
                (len(parts) == 2 and parts[0] in ("self", "cls")
                 and parts[1] in ctx.vocab.snapshot_attrs)
            if not is_snap:
                continue
            attr = node.attr
            if attr.startswith("_") or attr == "as_tuple":
                continue
            if attr in tracked or not is_flag(attr):
                continue
            out.append(_finding(
                fi, node, "KEY001",
                f"snapshot read {base}.{attr} is reachable from a cached "
                "builder but the flag is not in PROGRAM_FLAGS (nor a key "
                "discriminant) — stale-program class"))
    return out


# ------------------------------------------------------------- KEY002

def _closure_offenses(expr: ast.expr,
                      ctx: KeyContext) -> Iterator[Tuple[ast.expr, str]]:
    """self/cls-rooted attribute chains in a builder bind that are not
    snapshot state or key-derived state."""
    if isinstance(expr, ast.IfExp):
        yield from _closure_offenses(expr.body, ctx)
        yield from _closure_offenses(expr.orelse, ctx)
        return
    chain = _dotted(expr)
    if chain is None:
        return
    parts = chain.split(".")
    if parts[0] not in ("self", "cls") or len(parts) < 2:
        return
    attr = parts[1]
    if attr in ctx.vocab.snapshot_attrs or \
            attr in ctx.vocab.derived_attrs:
        return
    yield expr, chain


def _is_nested_in(inner: FunctionInfo, outer: FunctionInfo) -> bool:
    cur = inner.parent
    while cur is not None:
        if cur is outer:
            return True
        cur = cur.parent
    return False


def key002_builder_closure(fi: FunctionInfo,
                           ctx: KeyContext) -> List[Finding]:
    out: List[Finding] = []
    for adm in ctx.admissions:
        if adm.fi is not fi:
            continue
        for pname, vexpr in adm.binds:
            for node, chain in _closure_offenses(vexpr, ctx):
                out.append(_finding(
                    fi, node, "KEY002",
                    f"builder binds {pname}={chain} — mutable engine "
                    "state not derivable from the key; a second engine "
                    "sharing this key gets this engine's object"))
        for bfi in adm.builder_fis:
            if not _is_nested_in(bfi, fi):
                continue
            # a local-closure builder: its body may capture self.* from
            # the admitting method's scope
            for node in _body_walk(bfi):
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id in ("self", "cls") and \
                        node.attr not in ctx.vocab.snapshot_attrs and \
                        node.attr not in ctx.vocab.derived_attrs:
                    out.append(_finding(
                        fi, node, "KEY002",
                        f"local builder '{bfi.name}' closes over "
                        f"self.{node.attr} — mutable engine state not "
                        "derivable from the key"))
    return out


# ------------------------------------------------------------- KEY003

_UNHASHABLE = (ast.Dict, ast.Set, ast.List, ast.ListComp, ast.SetComp,
               ast.DictComp, ast.GeneratorExp)


def _hygiene_offenses(fi: FunctionInfo, expr: ast.expr,
                      depth: int = 0) -> Iterator[Tuple[ast.AST, str]]:
    if depth > 4:
        return
    if isinstance(expr, ast.Tuple):
        for el in expr.elts:
            yield from _hygiene_offenses(fi, el, depth + 1)
        return
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        yield from _hygiene_offenses(fi, expr.left, depth + 1)
        yield from _hygiene_offenses(fi, expr.right, depth + 1)
        return
    if isinstance(expr, _UNHASHABLE):
        yield expr, ("unhashable "
                     f"{type(expr).__name__.lower()} in a key component")
        return
    if isinstance(expr, ast.Constant) and isinstance(expr.value, float):
        yield expr, "raw float constant in a key component"
        return
    if isinstance(expr, ast.Call):
        tail = _tail(callee_name(expr))
        if tail == "float":
            yield expr, "raw float in a key component"
            return
        if tail in ("id", "hash"):
            yield expr, (f"{tail}() in a key component — identity "
                         "hashing breaks cross-engine sharing")
            return
    dev = device_producing(fi, expr)
    if dev is not None:
        yield expr, (f"device-producing '{dev}' in a key component — "
                     "keys must be host values (a device array forces "
                     "a sync and hashes by identity)")


def key003_component_hygiene(fi: FunctionInfo,
                             ctx: KeyContext) -> List[Finding]:
    out: List[Finding] = []
    for site in _sites_of(fi, ctx):
        for fname, vexpr in site.fields:
            for node, why in _hygiene_offenses(fi, vexpr):
                out.append(_finding(
                    fi, node, "KEY003", f"DecodeKey {fname}: {why}"))
    return out


# ------------------------------------------------------------- KEY004

_STEP_NAMES = frozenset({"step", "steps", "counter", "counters", "tick",
                         "ticks", "iteration", "iterations", "seq_no",
                         "now", "t_now"})
_CLOCK_TAILS = frozenset({"perf_counter", "monotonic", "time_ns",
                          "process_time", "clock"})
_RNG_TAILS = frozenset({"random", "randint", "uuid1", "uuid4",
                        "getrandbits", "token_hex", "getpid"})


def _dispatch_varying(fi: FunctionInfo,
                      expr: ast.expr) -> Iterator[Tuple[ast.AST, str]]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = callee_name(node) or ""
            tail = _tail(name)
            if tail in _CLOCK_TAILS or \
                    (tail == "time" and name.split(".")[0] == "time"):
                yield node, f"clock read {name}()"
            elif tail in _RNG_TAILS:
                yield node, f"rng/identity call {name}()"
            elif tail == "len" and node.args:
                chain = _dotted(node.args[0]) or ""
                if chain.split(".")[0] in ("self", "cls"):
                    yield node, (f"len({chain}) — a live container "
                                 "length; key the bucket, not the load")
        elif isinstance(node, ast.Attribute):
            if node.attr.lstrip("_") in _STEP_NAMES:
                yield node, f"step-like attribute .{node.attr}"
        elif isinstance(node, ast.Name):
            if node.id.lstrip("_") in _STEP_NAMES:
                yield node, f"step-like name '{node.id}'"


def key004_per_dispatch_value(fi: FunctionInfo,
                              ctx: KeyContext) -> List[Finding]:
    out: List[Finding] = []
    for site in _sites_of(fi, ctx):
        for fname, vexpr in site.fields:
            for node, why in _dispatch_varying(fi, vexpr):
                out.append(_finding(
                    fi, node, "KEY004",
                    f"DecodeKey {fname}: {why} — per-dispatch-varying "
                    "values retrace on every call"))
    return out


# ------------------------------------------------------------- KEY005

def _routes_through_invalidation(fi: FunctionInfo,
                                 ctx: KeyContext) -> bool:
    candidates = [fi]
    for call in fi.calls:
        candidates.extend(ctx.graph.resolve_call(fi, call))
    site_fis = {id(s.fi) for s in ctx.key_sites}
    for cand in candidates:
        if id(cand) in site_fis:
            return True
        for call in cand.calls:
            if _tail(callee_name(call)) == "clear_decode_program_cache":
                return True
    return False


def key005_invalidation_discipline(fi: FunctionInfo,
                                   ctx: KeyContext) -> List[Finding]:
    touched = [s for s in ctx.set_sites
               if s.fi is fi and set(s.names) & ctx.program_flags]
    if not touched:
        return []
    if _routes_through_invalidation(fi, ctx):
        return []
    out: List[Finding] = []
    for s in touched:
        names = ", ".join(sorted(set(s.names) & ctx.program_flags))
        out.append(_finding(
            fi, s.node, "KEY005",
            f"sets PROGRAM_FLAGS member(s) {names} without routing "
            "through clear_decode_program_cache() or minting a new key "
            "— cached programs keep the old flag tuple's fault/banking "
            "binding until re-armed"))
    return out


# ------------------------------------------------------------- KEY006

def key006_extra_grammar(fi: FunctionInfo,
                         ctx: KeyContext) -> List[Finding]:
    out: List[Finding] = []
    for site in _sites_of(fi, ctx):
        for node, s in site.unregistered:
            out.append(_finding(
                fi, node, "KEY006",
                f"extra tag/atom '{s}' is not registered in "
                "analysis/key_vocab.py — register it in "
                "EXTRA_TAGS/EXTRA_ATOMS so other key families cannot "
                "collide with it"))
    minter = ctx.minters.get(id(fi))
    if minter is not None:
        for node, s in minter.appended_unregistered:
            out.append(_finding(
                fi, node, "KEY006",
                f"extra tag/atom '{s}' appended by minter "
                f"'{fi.qualname}' is not registered in "
                "analysis/key_vocab.py"))
    for site, kind, gram, prior_gram, prior in ctx.schema_conflicts:
        if site.fi is not fi:
            continue
        out.append(_finding(
            fi, site.node, "KEY006",
            f"kind '{kind}' keys extra schema {list(gram)} here but "
            f"{list(prior_gram)} at {prior.fi.module.relpath}:"
            f"{prior.node.lineno} — one kind = one extra schema"))
    return out
