"""statecheck — a host-state handoff & cross-process serialization
discipline analyzer.

tracecheck (r08) gates *trace* discipline, meshcheck (r11) *collective*
discipline, faultcheck (r15) *recovery* discipline, and kernelcheck
(r20) *kernel* discipline; statecheck gates the bug class the
cross-process fleet arc (RPC/queue transport, prefill/decode
disaggregation, elastic rescale) will otherwise discover in
production: in-process handoffs pass by *reference*, so a device
array, a live mutable alias, or a bound streaming callback inside a
bundle works perfectly single-process and fails only the day the
transport serializes it.  Transportability is a static property —
check it before the transport exists.

Rules (all pure AST over the shared tracecheck parse):

- **STC001** device-backed (``jnp``/``lax``/jax-rooted) expression
  assigned into a bundle field outside a concretizer (generalizes
  faultcheck FLT003 from replay classes to the full bundle
  vocabulary, dict bundles included).
- **STC002** untransportable member reachable in a bundle type —
  locks, threads, generators, lambdas/bound methods/closures, jax
  objects, device pools.
- **STC003** exporter/adopter field symmetry + schema-version
  discipline: the fields the exporter writes and the adopter reads
  must match exactly, every dict bundle carries a version tag the
  adopter checks, one bundle name = one field set package-wide.
- **STC004** post-export aliasing — mutating a self-rooted mutable
  object after it was placed in an exported bundle
  (statement-dominance scan; copy/``detach``/``take_*`` resets).
- **STC005** nondeterministic cross-process identity — ids minted
  from ``id()``/``hash()``/clocks/uuid1/getpid (the r11
  ``CommGroup.id`` bug class made static).
- **STC006** callback discipline — callables are stripped at export
  and re-bound via an engine-local registry on adopt (the
  ``take_callbacks()``/``inject_request(on_token=)`` seam).

The bundle vocabulary (:mod:`.bundle_vocab`) is shared with faultcheck
— FLT003's replay vocabulary imports from here, so the two suites can
never drift.

Findings support inline ``# statecheck: disable=STC00x`` pragmas
(suite-scoped: another suite's pragma never silences STC rules) and a
checked-in baseline (tools/statecheck_baseline.json, kept empty — the
precedent is fix, don't baseline); the tier-1 test gates NEW findings
only.

Run it locally::

    python tools/analyze.py                     # all five suites
    python tools/analyze.py --suite statecheck
    python tools/statecheck.py --json           # census included
"""

from ..tracecheck.findings import (Finding, fingerprint, load_baseline,
                                   subtract_baseline, write_baseline)
from .analyzer import AnalyzerConfig, AnalysisResult, analyze_package
from .bundle_vocab import (bundle_class_vocabulary,
                           replay_class_vocabulary)
from .rules import STATE_RULES

__all__ = [
    "AnalyzerConfig", "AnalysisResult", "Finding", "STATE_RULES",
    "analyze_package", "bundle_class_vocabulary", "fingerprint",
    "load_baseline", "replay_class_vocabulary", "subtract_baseline",
    "write_baseline",
]
