"""Orchestration: parse (or reuse a parse), build the handoff model,
run the STC rules.

``analyze_package`` mirrors the other suites' entry points and accepts
the same :class:`ParsedPackage`, so the unified CLI (tools/analyze.py)
runs all FIVE suites over ONE ast.parse pass.  The context build is
read-only over the shared ``ModuleInfo`` objects, so running statecheck
never changes what the other suites report on the same parse, in
either order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..tracecheck.analyzer import ParsedPackage, parse_package
from ..tracecheck.callgraph import CallGraph, FunctionInfo
from ..tracecheck.findings import (Finding, dedupe_findings,
                                   parse_pragmas, suppressed)
from .state_model import build_context
from . import rules as SR


@dataclass
class AnalyzerConfig:
    exclude_patterns: tuple = ()
    rules: tuple = ("STC001", "STC002", "STC003", "STC004", "STC005",
                    "STC006")


@dataclass
class AnalysisResult:
    findings: List[Finding]              # post-pragma, pre-baseline
    suppressed: List[Finding]            # pragma-silenced
    n_files: int = 0
    n_functions: int = 0
    n_bundle_classes: int = 0            # vocabulary classes defined here
    n_exporters: int = 0                 # exporter seam functions
    n_adopters: int = 0                  # adopter seam functions
    n_seam_pairs: int = 0                # paired exporter/adopter groups
    n_dict_bundles: int = 0              # dict-returning exporters
    census: Dict[str, object] = field(default_factory=dict)
    errors: List[str] = field(default_factory=list)


_RULE_FNS = {
    "STC001": SR.stc001_device_in_bundle,
    "STC002": SR.stc002_untransportable_member,
    "STC003": SR.stc003_schema_discipline,
    "STC004": SR.stc004_post_export_alias,
    "STC005": SR.stc005_nondeterministic_identity,
    "STC006": SR.stc006_callback_in_bundle,
}


def analyze_package(package_path: str,
                    config: Optional[AnalyzerConfig] = None,
                    parsed: Optional[ParsedPackage] = None
                    ) -> AnalysisResult:
    config = config or AnalyzerConfig()
    if parsed is None:
        parsed = parse_package(package_path, config.exclude_patterns)
    else:
        parsed = parsed.filtered(config.exclude_patterns)

    result = AnalysisResult(findings=[], suppressed=[])
    result.errors = list(parsed.errors)
    result.n_files = parsed.n_files

    graph = CallGraph(parsed.modules, parsed.package)
    ctx = build_context(parsed.modules, graph)
    pairs = ctx.seam_pairs
    result.n_bundle_classes = len(ctx.class_defs)
    result.n_exporters = len(ctx.exporters)
    result.n_adopters = len(ctx.adopters)
    result.n_seam_pairs = len(pairs)
    result.n_dict_bundles = len(ctx.dict_bundles)
    result.census = {
        "bundle_classes": sorted(ctx.class_defs),
        "vocabulary": sorted(ctx.bundle_classes),
        "exporters": sorted(fi.qualname for fi in
                            ctx.exporters.values()),
        "adopters": sorted(fi.qualname for fi in
                           ctx.adopters.values()),
        "seam_pairs": [list(p) for p in pairs],
        "dict_bundles": sorted(
            ({"exporter": db.fi.qualname, "keys": sorted(db.keys),
              "version_key": db.version_key}
             for db in ctx.dict_bundles.values()),
            key=lambda d: d["exporter"]),
    }

    findings: List[Finding] = []
    for mod in parsed.modules.values():
        pragmas = parse_pragmas(mod.source_lines, tool="statecheck")
        fis = list(mod.functions.values())
        if "" not in mod.functions:
            # the indexer creates the module-body FunctionInfo lazily
            # (only when a top-level call exists); STC002's class-level
            # field scan anchors there, so synthesize a transient one —
            # NEVER stored back into the shared parse
            fis.append(FunctionInfo("", mod.tree, mod, None, None))
        for fi in fis:
            result.n_functions += 1
            batch: List[Finding] = []
            for code in config.rules:
                fn = _RULE_FNS.get(code)
                if fn is not None:
                    batch += fn(fi, ctx)
            for f in batch:
                (result.suppressed if suppressed(f, pragmas)
                 else findings).append(f)

    result.findings = dedupe_findings(findings)
    return result
