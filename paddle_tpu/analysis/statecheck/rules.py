"""The STC rule checkers.

Each rule is ``(FunctionInfo, StateContext) -> List[Finding]`` over ONE
function body (nested defs are their own FunctionInfo).  The rules
encode the contract the cross-process fleet arc rests on: a handoff
bundle must survive serialization and mean the same thing on the other
side — host values only, no untransportable members, one schema per
bundle name with a version tag, no live aliases after export, no
per-process identities, and callbacks stripped at export / re-bound via
registry on adopt.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..tracecheck import rules as R
from ..tracecheck.callgraph import FunctionInfo, _dotted, callee_name
from ..tracecheck.findings import Finding
from .bundle_vocab import device_producing, is_concretizer_call
from .state_model import StateContext, VERSION_KEYS, _walk_stmts

STATE_RULES: Dict[str, str] = {
    "STC001": "device-backed expression assigned into a handoff-bundle "
              "field outside a concretizer — a jnp/lax/jax-rooted "
              "value stored in a bundle dies with its process's device "
              "state and cannot serialize; concretize first "
              "(int()/np.asarray()/.item()/jax.device_get)",
    "STC002": "untransportable member reachable in a bundle type — a "
              "lock/thread/generator/callable/jax-object/device-pool "
              "member makes every instance unpicklable (or silently "
              "wrong) the day the transport serializes it; keep such "
              "state engine-local and re-derive it on adopt",
    "STC003": "exporter/adopter field symmetry + schema-version "
              "discipline — the fields an exporter writes and its "
              "paired adopter reads must match exactly, every dict "
              "bundle carries a version tag the adopter checks, and "
              "one bundle name keeps ONE field set package-wide",
    "STC004": "post-export aliasing — a self-rooted mutable object "
              "mutated after it was placed in an exported bundle: "
              "in-process the receiver sees the mutation, across a "
              "process boundary the serialized snapshot silently "
              "diverges; copy at placement or hand ownership off "
              "(take_*/detach_*)",
    "STC005": "nondeterministic cross-process identity — an id minted "
              "from id()/hash()/clocks/uuid1/getpid is only unique (or "
              "only stable) within one process; two processes mint "
              "colliding or irreproducible keys, so derive identities "
              "from a process-stable key instead",
    "STC006": "callback discipline — a callable placed in a handoff "
              "bundle (lambda, bound method, closure, Callable "
              "parameter) cannot cross a process boundary; strip it at "
              "export and re-bind via an engine-local registry on "
              "adopt (the take_callbacks()/inject_request(on_token=) "
              "seam)",
}


def _finding(fi: FunctionInfo, node: ast.AST, rule: str,
             msg: str) -> Finding:
    line = getattr(node, "lineno", fi.lineno)
    return Finding(rule=rule, path=fi.module.relpath, line=line,
                   func=fi.qualname, message=msg,
                   source=fi.module.line(line))


# ---------------------------------------------------------- bundle instances
def _bundle_instances(fi: FunctionInfo, ctx: StateContext) -> Set[str]:
    """Local names holding bundle instances in this function:
    parameters annotated with a bundle class, locals constructed from
    one, and — in modules that define/import a bundle class — the
    conventional ``req``/``request`` names (the FLT003 convention)."""
    out: Set[str] = set()
    node = fi.node
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for p in (node.args.posonlyargs + node.args.args
                  + node.args.kwonlyargs):
            ann = p.annotation
            if ann is not None and any(
                    isinstance(s, ast.Name)
                    and s.id in ctx.bundle_classes
                    for s in ast.walk(ann)):
                out.add(p.arg)
        for stmt in R._body_walk(fi):
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                vn = callee_name(stmt.value)
                if vn and vn.rsplit(".", 1)[-1] in ctx.bundle_classes:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
    mod = fi.module
    mod_has_bundle = any(
        imp[1] in ctx.bundle_classes
        for imp in mod.imported_names.values())
    if not mod_has_bundle:
        for sub in mod.tree.body:
            if isinstance(sub, ast.ClassDef) and \
                    sub.name in ctx.bundle_classes:
                mod_has_bundle = True
                break
    if mod_has_bundle:
        out.update(("req", "request"))
    return out


def _field_stores(fi: FunctionInfo, insts: Set[str]):
    """Yield ``(anchor_node, field_chain, value_expr)`` for every store
    into a bundle instance: attribute/subscript assigns and
    append/extend/insert mutations."""
    for node in R._body_walk(fi):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                chain = _dotted(t)
                if chain and "." in chain and \
                        chain.split(".")[0] in insts:
                    yield node, chain, node.value
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("append", "extend", "insert") and \
                node.args:
            chain = _dotted(node.func.value)
            if chain and chain.split(".")[0] in insts:
                yield node, chain, node.args[-1]


# ------------------------------------------------------------------ STC001
def stc001_device_in_bundle(fi: FunctionInfo, ctx: StateContext
                            ) -> List[Finding]:
    """FLT003 generalized: device-producing expressions stored into ANY
    bundle-vocabulary instance, plus the values of exporter dict
    bundles."""
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    out: List[Finding] = []
    insts = _bundle_instances(fi, ctx)
    if insts:
        for node, chain, value in _field_stores(fi, insts):
            culprit = device_producing(fi, value)
            if culprit is not None:
                out.append(_finding(
                    fi, node, "STC001",
                    f"bundle field {chain} assigned from {culprit}(...)"
                    " — handoff bundles must be pure host values; a "
                    "device value here dies with this process's pool "
                    "and cannot serialize across the transport; "
                    "concretize first (int()/np.asarray()/"
                    "jax.device_get)"))
    db = ctx.dict_bundles.get(id(fi))
    if db is not None:
        for key, value in sorted(db.values.items()):
            culprit = device_producing(fi, value)
            if culprit is not None:
                out.append(_finding(
                    fi, value, "STC001",
                    f"dict-bundle field '{key}' assigned from "
                    f"{culprit}(...) — the exported bundle must be "
                    "pure host values; concretize before placing it "
                    "(int()/np.asarray()/jax.device_get)"))
    return out


# ------------------------------------------------------------------ STC002
_UNTRANSPORTABLE_ANN = frozenset({
    "Callable", "Lock", "RLock", "Thread", "Event", "Condition",
    "Semaphore", "BoundedSemaphore", "Barrier", "Queue", "LifoQueue",
    "Generator", "Iterator", "AsyncIterator", "Coroutine",
    "ThreadPoolExecutor", "ProcessPoolExecutor", "Array", "Tracer",
    "ArrayImpl", "DeviceArray",
})
_UNTRANSPORTABLE_SUFFIX = re.compile(r"(Pool|KVCache|Executor|Socket|"
                                     r"Client|Server)$")
_UNTRANSPORTABLE_CTOR_TAILS = frozenset({
    "Lock", "RLock", "Event", "Condition", "Semaphore",
    "BoundedSemaphore", "Barrier", "Thread", "Queue", "LifoQueue",
    "ThreadPoolExecutor", "ProcessPoolExecutor",
})


def _ann_untransportable(ann: ast.AST) -> Optional[str]:
    for sub in ast.walk(ann):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is None:
            continue
        if name in _UNTRANSPORTABLE_ANN or \
                _UNTRANSPORTABLE_SUFFIX.search(name):
            return name
    return None


def _method_names(cls: ast.ClassDef) -> Set[str]:
    return {s.name for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _value_untransportable(fi: FunctionInfo, value: ast.expr,
                           methods: Set[str]) -> Optional[str]:
    if isinstance(value, ast.Lambda):
        return "a lambda"
    if isinstance(value, (ast.GeneratorExp,)):
        return "a generator expression"
    if isinstance(value, ast.Call):
        name = callee_name(value)
        if name is not None:
            tail = name.rsplit(".", 1)[-1]
            if tail in _UNTRANSPORTABLE_CTOR_TAILS:
                return f"{tail}()"
        culprit = device_producing(fi, value)
        if culprit is not None:
            return f"{culprit}(...) (a device value)"
        return None
    if isinstance(value, ast.Attribute):
        chain = _dotted(value)
        if chain and chain.startswith(("self.", "cls.")) and \
                chain.split(".")[-1] in methods:
            return f"the bound method {chain}"
    return None


def stc002_untransportable_member(fi: FunctionInfo, ctx: StateContext
                                  ) -> List[Finding]:
    """Scan bundle-class bodies: annotated fields (class level and
    ``__init__`` parameters stored onto self) and ``self.x = ...``
    member builds must stay transportable.  Findings attach to the
    class's functions (``__init__``/methods) or — for class-level
    annotations — to the module body's FunctionInfo."""
    out: List[Finding] = []
    # class-level annotated fields: report once, from the module-body
    # FunctionInfo (qualname ""), anchored at the AnnAssign line
    if isinstance(fi.node, ast.Module):
        for cname, (mod, cls) in sorted(ctx.class_defs.items()):
            if mod is not fi.module:
                continue
            for stmt in cls.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        stmt.annotation is not None:
                    bad = _ann_untransportable(stmt.annotation)
                    if bad is not None:
                        tname = (_dotted(stmt.target)
                                 or "<field>")
                        out.append(_finding(
                            fi, stmt, "STC002",
                            f"bundle class {cname} declares field "
                            f"{tname} as {bad} — an untransportable "
                            "member makes every exported instance "
                            "unpicklable (or dead on arrival) across "
                            "a process boundary; keep it engine-local "
                            "(registry/pool) and re-bind on adopt"))
        return out
    if not fi.cls or fi.cls not in ctx.class_defs:
        return []
    mod, cls = ctx.class_defs[fi.cls]
    if mod is not fi.module:
        return []
    methods = _method_names(cls)
    node = fi.node
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # parameters stored onto self with untransportable annotations
        ann_of = {p.arg: p.annotation
                  for p in (node.args.posonlyargs + node.args.args
                            + node.args.kwonlyargs)
                  if p.annotation is not None}
        for stmt in R._body_walk(fi):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            self_targets = [
                t for t in targets
                if (_dotted(t) or "").startswith(("self.", "cls."))]
            if not self_targets:
                continue
            value = stmt.value
            if value is None:
                continue
            bad: Optional[str] = None
            if isinstance(value, ast.Name) and value.id in ann_of:
                got = _ann_untransportable(ann_of[value.id])
                if got is not None:
                    bad = f"the {got}-annotated parameter {value.id}"
            if bad is None:
                bad = _value_untransportable(fi, value, methods)
            if bad is not None:
                chain = _dotted(self_targets[0]) or "self.<member>"
                out.append(_finding(
                    fi, stmt, "STC002",
                    f"bundle class {fi.cls} binds member {chain} to "
                    f"{bad} — an untransportable member makes every "
                    "exported instance unpicklable (or dead on "
                    "arrival) across a process boundary; keep it "
                    "engine-local (registry/pool) and re-bind on "
                    "adopt"))
    return out


# ------------------------------------------------------------------ STC003
def stc003_schema_discipline(fi: FunctionInfo, ctx: StateContext
                             ) -> List[Finding]:
    out: List[Finding] = []
    db = ctx.dict_bundles.get(id(fi))
    if db is not None and not db.dynamic:
        stem = db.group[1]
        if db.version_key is None:
            out.append(_finding(
                fi, db.node, "STC003",
                f"dict bundle '{stem}' carries no schema-version tag "
                f"(one of {sorted(VERSION_KEYS)}) — a cross-process "
                "pair built from different revisions would mis-read "
                "the bundle instead of refusing loudly; write a "
                "version key and validate it at adopt"))
        # field symmetry vs every paired adopter that does keyed reads
        ex, ad = ctx.pair_groups.get(db.group, ([], []))
        for adopter in ad:
            reads = ctx.adopter_reads.get(id(adopter))
            if reads is None:
                continue
            missing = sorted(reads.keys - db.keys)
            unread = sorted(db.keys - reads.keys)
            if missing or unread:
                detail = []
                if unread:
                    detail.append("written but never read: "
                                  + ", ".join(unread))
                if missing:
                    detail.append("read but never written: "
                                  + ", ".join(missing))
                out.append(_finding(
                    fi, db.node, "STC003",
                    f"dict bundle '{stem}' field asymmetry vs adopter "
                    f"{adopter.qualname} ({'; '.join(detail)}) — the "
                    "exporter's field set and the adopter's reads "
                    "must match exactly, or a schema drift ships "
                    "silently"))
            if db.version_key is not None and not reads.version_read:
                out.append(_finding(
                    fi, db.node, "STC003",
                    f"dict bundle '{stem}' writes version key "
                    f"'{db.version_key}' but adopter "
                    f"{adopter.qualname} never reads it — an "
                    "unchecked version tag is no version discipline; "
                    "validate it before seating the bundle"))
        # one bundle name = one field set package-wide
        for other in ctx.dict_bundles.values():
            if other.fi is db.fi or other.dynamic:
                continue
            if other.group[1] == stem and other.keys != db.keys and \
                    (other.fi.module.relpath, other.fi.qualname) < \
                    (fi.module.relpath, fi.qualname):
                out.append(_finding(
                    fi, db.node, "STC003",
                    f"dict bundle '{stem}' written here with fields "
                    f"{sorted(db.keys)} but at "
                    f"{other.fi.module.relpath}:{other.node.lineno} "
                    f"with {sorted(other.keys)} — one bundle name "
                    "keeps ONE field set package-wide (the FLT005 "
                    "metric-schema idiom applied to bundles)"))
    return out


# ------------------------------------------------------------------ STC004
_TRANSPORT_TAILS = frozenset({"dumps", "dump", "send", "send_bytes",
                              "put", "put_nowait", "publish"})
_COPY_TAILS = frozenset({"list", "dict", "tuple", "copy", "deepcopy",
                         "array", "asarray", "frombuffer"})
_MUTATOR_TAILS = frozenset({"append", "extend", "insert", "pop",
                            "clear", "update", "remove", "setdefault",
                            "sort", "reverse"})


def _placed_value_chain(value: ast.expr) -> Optional[str]:
    """The self-rooted chain a bundle member aliases, or None when the
    placement copies (list()/np.array()/copy.deepcopy) or detaches
    (take_*/detach_*) the value."""
    if isinstance(value, ast.Call):
        name = callee_name(value)
        if name is not None:
            tail = name.rsplit(".", 1)[-1]
            if tail in _COPY_TAILS or R._is_handoff_call(value):
                return None
        return None                      # call results are fresh values
    base = value
    while isinstance(base, ast.Subscript):
        base = base.value
    chain = _dotted(base)
    if chain is not None and chain.split(".")[0] in ("self", "cls"):
        return chain
    return None


def stc004_post_export_alias(fi: FunctionInfo, ctx: StateContext
                             ) -> List[Finding]:
    """Statement-dominance scan (the FLT002 shape): placing a
    self-rooted object into a local bundle records the alias; a
    transport call (pickle.dumps/send/put/publish) on that bundle marks
    the export point; mutating a placed alias afterwards is a finding.
    Rebinding the bundle local clears its region."""
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    out: List[Finding] = []
    placed: Dict[str, Dict[str, ast.AST]] = {}   # bundle -> chain -> node
    exported: Dict[str, ast.stmt] = {}           # bundle -> export stmt

    def record_placement(bundle: str, value: ast.expr) -> None:
        chain = _placed_value_chain(value)
        if chain is not None:
            placed.setdefault(bundle, {})[chain] = value

    def stmt_mutates(stmt: ast.stmt) -> Optional[Tuple[str, str]]:
        """(bundle, chain) when this statement mutates a placed alias
        of an already-exported bundle."""
        chains: List[str] = []
        for node in _walk_stmts([stmt]):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    c = _dotted(base)
                    if c is not None and "." in c:
                        chains.append(c)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATOR_TAILS:
                c = _dotted(node.func.value)
                if c is not None:
                    chains.append(c)
        for bundle in exported:
            for chain in chains:
                for pchain in placed.get(bundle, {}):
                    if chain == pchain or \
                            chain.startswith(pchain + "."):
                        return bundle, pchain
        return None

    def scan(stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            # placements: b = {...} / b["k"] = self.x / b.append(self.x)
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        # rebinding the local starts a fresh bundle
                        placed.pop(t.id, None)
                        exported.pop(t.id, None)
                        if isinstance(stmt.value, ast.Dict):
                            for v in stmt.value.values:
                                if v is not None:
                                    record_placement(t.id, v)
                        elif isinstance(stmt.value, (ast.List,
                                                     ast.Tuple)):
                            for v in stmt.value.elts:
                                record_placement(t.id, v)
                    elif isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name):
                        record_placement(t.value.id, stmt.value)
            hit = stmt_mutates(stmt)
            if hit is not None:
                bundle, chain = hit
                out.append(_finding(
                    fi, stmt, "STC004",
                    f"{chain} mutated after being placed in bundle "
                    f"'{bundle}', which was exported at line "
                    f"{exported[bundle].lineno} — in-process the "
                    "receiver sees the mutation, across a process "
                    "boundary the serialized snapshot silently "
                    "diverges; copy at placement (np.array/list()) or "
                    "hand ownership off (take_*/detach_*) before "
                    "mutating"))
            for sub in _walk_stmts([stmt]):
                if isinstance(sub, ast.Call):
                    name = callee_name(sub)
                    if name is None:
                        continue
                    if name.rsplit(".", 1)[-1] in _TRANSPORT_TAILS:
                        for arg in sub.args:
                            if isinstance(arg, ast.Name) and \
                                    arg.id in placed:
                                exported.setdefault(arg.id, stmt)
                    # b.append(self.x) placement
                    if isinstance(sub.func, ast.Attribute) and \
                            sub.func.attr in ("append", "insert",
                                              "extend") and \
                            isinstance(sub.func.value, ast.Name) and \
                            sub.args:
                        record_placement(sub.func.value.id,
                                         sub.args[-1])

    scan(list(fi.node.body))
    return out


# ------------------------------------------------------------------ STC005
_ID_FIELD = re.compile(r"(^|_)(id|uid|gid|rid|lid|uuid|key)$")
_NONDET_TAILS = frozenset({"id", "hash", "uuid1", "uuid4", "getpid",
                           "time", "time_ns", "monotonic",
                           "monotonic_ns", "perf_counter",
                           "perf_counter_ns", "random", "randint",
                           "randrange", "getrandbits", "token_hex",
                           "token_bytes", "urandom"})


def _nondet_call(expr: ast.expr) -> Optional[str]:
    for sub in _walk_stmts([expr]):
        if not isinstance(sub, ast.Call):
            continue
        name = callee_name(sub)
        if name is None:
            continue
        parts = name.split(".")
        tail = parts[-1]
        if tail not in _NONDET_TAILS:
            continue
        if tail in ("id", "hash") and len(parts) > 1:
            continue                    # obj.id()/x.hash() is a method,
                                        # not the process-local builtin
        return name
    return None


def stc005_nondeterministic_identity(fi: FunctionInfo, ctx: StateContext
                                     ) -> List[Finding]:
    """Identity fields of bundle instances (``*.rid``/``*.key``/...)
    and id-ish dict-bundle values must not be minted from process-local
    sources (id()/hash()/clocks/uuid1/getpid/random)."""
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    out: List[Finding] = []
    insts = _bundle_instances(fi, ctx)
    if insts:
        for node, chain, value in _field_stores(fi, insts):
            fld = chain.rsplit(".", 1)[-1]
            if not _ID_FIELD.search(fld):
                continue
            culprit = _nondet_call(value)
            if culprit is not None:
                out.append(_finding(
                    fi, node, "STC005",
                    f"bundle identity field {chain} minted from "
                    f"{culprit}(...) — id()/hash()/clocks/uuid1/getpid "
                    "are process-local: ids collide or change across "
                    "the process boundary (the CommGroup.id bug class)"
                    "; derive identities from a process-stable key"))
    db = ctx.dict_bundles.get(id(fi))
    if db is not None:
        for key, value in sorted(db.values.items()):
            if not _ID_FIELD.search(key):
                continue
            culprit = _nondet_call(value)
            if culprit is not None:
                out.append(_finding(
                    fi, value, "STC005",
                    f"dict-bundle identity field '{key}' minted from "
                    f"{culprit}(...) — process-local identity sources "
                    "collide or change across the process boundary; "
                    "derive identities from a process-stable key"))
    return out


# ------------------------------------------------------------------ STC006
def _local_defs(fi: FunctionInfo) -> Set[str]:
    names: Set[str] = set()
    node = fi.node
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for stmt in ast.walk(node):
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    stmt is not node:
                names.add(stmt.name)
    return names


def _callable_params(fi: FunctionInfo) -> Set[str]:
    node = fi.node
    out: Set[str] = set()
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for p in (node.args.posonlyargs + node.args.args
                  + node.args.kwonlyargs):
            ann = p.annotation
            if ann is not None and any(
                    isinstance(s, ast.Name) and s.id == "Callable"
                    for s in ast.walk(ann)):
                out.add(p.arg)
    return out


def _callable_value(fi: FunctionInfo, value: ast.expr,
                    local_defs: Set[str],
                    callable_params: Set[str]) -> Optional[str]:
    if isinstance(value, ast.Lambda):
        return "a lambda"
    if isinstance(value, ast.Name):
        if value.id in local_defs:
            return f"the nested function {value.id} (a closure)"
        if value.id in callable_params:
            return f"the Callable parameter {value.id}"
    if isinstance(value, ast.Call):
        name = callee_name(value)
        if name and name.rsplit(".", 1)[-1] == "partial":
            return f"{name}(...) (a bound partial)"
    return None


def stc006_callback_in_bundle(fi: FunctionInfo, ctx: StateContext
                              ) -> List[Finding]:
    """A callable flowing into a bundle-instance field or an exporter
    dict bundle.  The blessed pattern is an engine-local registry:
    strip at export (``take_callbacks()``), re-bind on adopt
    (``inject_request(..., on_token=)``)."""
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    out: List[Finding] = []
    local_defs = _local_defs(fi)
    callable_params = _callable_params(fi)
    insts = _bundle_instances(fi, ctx)
    if insts:
        for node, chain, value in _field_stores(fi, insts):
            bad = _callable_value(fi, value, local_defs,
                                  callable_params)
            if bad is not None:
                out.append(_finding(
                    fi, node, "STC006",
                    f"bundle field {chain} bound to {bad} — a "
                    "callable inside a handoff bundle cannot cross "
                    "the process boundary (closures/bound methods "
                    "drag live state with them); strip it at export "
                    "and re-bind via an engine-local registry on "
                    "adopt (take_callbacks()/inject_request("
                    "on_token=))"))
    db = ctx.dict_bundles.get(id(fi))
    if db is not None:
        for key, value in sorted(db.values.items()):
            bad = _callable_value(fi, value, local_defs,
                                  callable_params)
            if bad is not None:
                out.append(_finding(
                    fi, value, "STC006",
                    f"dict-bundle field '{key}' bound to {bad} — a "
                    "callable inside an exported bundle cannot cross "
                    "the process boundary; strip it at export and "
                    "re-bind via an engine-local registry on adopt"))
    return out
