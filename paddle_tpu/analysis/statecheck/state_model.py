"""The handoff model statecheck reasons over (pure AST, shared parse).

Four questions drive the STC rules:

1. **What is a bundle?**  The bundle-class vocabulary from
   :mod:`.bundle_vocab` (``Request``, ``HostPage``, seeds plus classes
   annotated on exporter/adopter seam signatures), restricted to
   classes actually DEFINED in the analyzed package for the class-body
   rules (STC002), plus the dict bundles exporters return.

2. **Where are the seams?**  Every function named with an exporter
   prefix (``export_``/``harvest_``/``spill_``) or an adopter prefix
   (``inject_``/``adopt_``/``restore_``).  Exporters and adopters pair
   by (owner class, seam stem) — ``harvest_request`` pairs with
   ``adopt_request`` on ``ServingEngine``, ``spill_page`` with
   ``adopt_page``/``restore_page`` on ``PagedKVCache``.  The pair
   census feeds STC003 and the scale-sanity gate.

3. **What does each dict bundle carry?**  For a dict-returning
   exporter: the string keys of the returned dict literal (plus
   ``b["k"] = ...`` writes into the returned local).  For an adopter:
   the keys it subscripts/``.get``\\ s off its bundle parameter.  STC003
   compares the two and demands a schema-version key.

4. **Which calls matter?**  The call graph resolves exporter/adopter
   call sites (fleet ``_lose_replica`` -> ``export_requests``) so the
   rules can scope alias and callback checks to code that actually
   feeds a seam.

Everything here is READ-ONLY over the shared :class:`ModuleInfo`
objects, so running statecheck never changes what the other suites
report on the same parse, in either order.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..tracecheck.callgraph import (CallGraph, FunctionInfo, ModuleInfo,
                                    _dotted, callee_name)
from ..tracecheck.rules import _body_walk
from .bundle_vocab import (bundle_class_vocabulary, is_adopter_name,
                           is_exporter_name, seam_stem)

# keys an exporter may use as the bundle's schema-version tag
VERSION_KEYS = frozenset({"v", "version", "schema", "schema_version"})


def _walk_stmts(stmts):
    """Pre-order walk of a statement list that PRUNES nested function
    bodies (a closure's statements belong to its own FunctionInfo)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@dataclass
class DictBundle:
    """One exporter-written dict bundle (the ``harvest_request``
    shape): the keys it returns, keyed by the seam's pairing group."""
    fi: FunctionInfo
    group: Tuple[str, str]               # (owner, stem)
    keys: frozenset                      # statically-known string keys
    values: Dict[str, ast.expr]          # key -> value expression
    node: ast.AST                        # the dict literal (anchor)
    version_key: Optional[str]           # which VERSION_KEYS member, if any
    dynamic: bool                        # non-constant key seen


@dataclass
class AdopterReads:
    """The dict-bundle keys one adopter reads off its parameter."""
    fi: FunctionInfo
    group: Tuple[str, str]
    keys: frozenset                      # subscript/.get string keys
    version_read: bool


@dataclass
class StateContext:
    graph: CallGraph
    bundle_classes: frozenset            # full vocabulary (names)
    class_defs: Dict[str, Tuple[ModuleInfo, ast.ClassDef]]
    exporters: Dict[int, FunctionInfo]   # id(fi) -> fi
    adopters: Dict[int, FunctionInfo]
    pair_groups: Dict[Tuple[str, str], Tuple[List[FunctionInfo],
                                             List[FunctionInfo]]]
    dict_bundles: Dict[int, DictBundle]  # id(fi) -> bundle
    adopter_reads: Dict[int, AdopterReads]
    fn_of: Dict[int, FunctionInfo] = field(default_factory=dict)

    @property
    def seam_pairs(self) -> List[Tuple[str, str]]:
        """Pairing groups with at least one exporter AND one adopter."""
        return sorted(g for g, (ex, ad) in self.pair_groups.items()
                      if ex and ad)


def _owner_of(fi: FunctionInfo) -> str:
    return fi.cls if fi.cls else fi.module.relpath


# --------------------------------------------------- dict-bundle extraction
def _dict_literal_keys(node: ast.Dict) -> Tuple[Set[str],
                                                Dict[str, ast.expr], bool]:
    keys: Set[str] = set()
    values: Dict[str, ast.expr] = {}
    dynamic = False
    for k, v in zip(node.keys, node.values):
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
            values[k.value] = v
        else:
            dynamic = True               # **spread or computed key
    return keys, values, dynamic


def extract_dict_bundle(fi: FunctionInfo) -> Optional[DictBundle]:
    """The dict bundle an exporter returns: ``return {literal}``, or
    ``return name`` where ``name`` was assigned a dict literal in this
    body (``b["k"] = ...`` writes between the two extend the keys)."""
    node = fi.node
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    returns = [s for s in _walk_stmts(node.body)
               if isinstance(s, ast.Return) and s.value is not None]
    lit: Optional[ast.Dict] = None
    local: Optional[str] = None
    for r in returns:
        if isinstance(r.value, ast.Dict):
            lit = r.value
            break
        if isinstance(r.value, ast.Name):
            local = r.value.id
    keys: Set[str] = set()
    values: Dict[str, ast.expr] = {}
    dynamic = False
    anchor: Optional[ast.AST] = lit
    if lit is not None:
        keys, values, dynamic = _dict_literal_keys(lit)
    elif local is not None:
        found = False
        for stmt in _walk_stmts(node.body):
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Dict) and any(
                        isinstance(t, ast.Name) and t.id == local
                        for t in stmt.targets):
                k, v, d = _dict_literal_keys(stmt.value)
                keys |= k
                values.update(v)
                dynamic = dynamic or d
                anchor = anchor or stmt.value
                found = True
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == local:
                        sl = t.slice
                        if isinstance(sl, ast.Constant) and \
                                isinstance(sl.value, str):
                            keys.add(sl.value)
                            values[sl.value] = stmt.value
                        else:
                            dynamic = True
        if not found:
            return None                  # returns something else
    else:
        return None
    version = next((k for k in sorted(keys) if k in VERSION_KEYS), None)
    return DictBundle(fi=fi, group=(_owner_of(fi), seam_stem(fi.name)),
                      keys=frozenset(keys), values=values,
                      node=anchor or node, version_key=version,
                      dynamic=dynamic)


def extract_adopter_reads(fi: FunctionInfo) -> Optional[AdopterReads]:
    """Keys this adopter reads off a dict-bundle parameter: subscripts
    and ``.get(...)`` calls with string-constant keys on any
    parameter.  None when the adopter never does a keyed read (it
    adopts a typed object, not a dict bundle)."""
    node = fi.node
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    params = {p.arg for p in (node.args.posonlyargs + node.args.args
                              + node.args.kwonlyargs)} - {"self", "cls"}
    if not params:
        return None
    keys: Set[str] = set()
    version_read = False
    for sub in _walk_stmts(node.body):
        key: Optional[str] = None
        if isinstance(sub, ast.Subscript) and \
                isinstance(sub.value, ast.Name) and \
                sub.value.id in params and \
                isinstance(sub.slice, ast.Constant) and \
                isinstance(sub.slice.value, str):
            key = sub.slice.value
        elif isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr == "get" and \
                isinstance(sub.func.value, ast.Name) and \
                sub.func.value.id in params and sub.args and \
                isinstance(sub.args[0], ast.Constant) and \
                isinstance(sub.args[0].value, str):
            key = sub.args[0].value
        if key is None:
            continue
        keys.add(key)
        if key in VERSION_KEYS:
            version_read = True
    if not keys:
        return None
    return AdopterReads(fi=fi, group=(_owner_of(fi), seam_stem(fi.name)),
                        keys=frozenset(keys), version_read=version_read)


# -------------------------------------------------------------- the build
def build_context(modules: Dict[str, ModuleInfo],
                  graph: CallGraph) -> StateContext:
    vocab = bundle_class_vocabulary(modules)

    class_defs: Dict[str, Tuple[ModuleInfo, ast.ClassDef]] = {}
    for mod in modules.values():
        for stmt in ast.walk(mod.tree):
            if isinstance(stmt, ast.ClassDef) and stmt.name in vocab:
                class_defs.setdefault(stmt.name, (mod, stmt))

    fn_of: Dict[int, FunctionInfo] = {}
    exporters: Dict[int, FunctionInfo] = {}
    adopters: Dict[int, FunctionInfo] = {}
    pair_groups: Dict[Tuple[str, str],
                      Tuple[List[FunctionInfo], List[FunctionInfo]]] = {}
    dict_bundles: Dict[int, DictBundle] = {}
    adopter_reads: Dict[int, AdopterReads] = {}

    for mod in modules.values():
        for fi in mod.functions.values():
            fn_of[id(fi)] = fi
            if isinstance(fi.node, (ast.Module, ast.Lambda)):
                continue
            if is_exporter_name(fi.name):
                exporters[id(fi)] = fi
                group = (_owner_of(fi), seam_stem(fi.name))
                pair_groups.setdefault(group, ([], []))[0].append(fi)
                db = extract_dict_bundle(fi)
                if db is not None:
                    dict_bundles[id(fi)] = db
            elif is_adopter_name(fi.name):
                adopters[id(fi)] = fi
                group = (_owner_of(fi), seam_stem(fi.name))
                pair_groups.setdefault(group, ([], []))[1].append(fi)
                ar = extract_adopter_reads(fi)
                if ar is not None:
                    adopter_reads[id(fi)] = ar

    return StateContext(
        graph=graph, bundle_classes=vocab, class_defs=class_defs,
        exporters=exporters, adopters=adopters,
        pair_groups=pair_groups, dict_bundles=dict_bundles,
        adopter_reads=adopter_reads, fn_of=fn_of)
