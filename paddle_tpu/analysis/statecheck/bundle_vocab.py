"""The ONE handoff-bundle vocabulary, shared by faultcheck and
statecheck.

faultcheck's FLT003 (r15) polices device values stored into *replay*
structures; statecheck (this round) generalizes the same vocabulary to
every host-state bundle that crosses — or will cross — a process
boundary: ``Request``, ``HostPage``, the ``harvest_request`` dict
bundle, emergency-checkpoint payloads, and any class annotated on an
exporter/adopter seam signature.  Both suites import the vocabulary
from HERE (the r20 ``tile_geometry`` unification pattern): one
definition, no drift — asserted by a no-drift test.

Also owned here: the *concretizer* vocabulary (host-value wrappers) and
the device-producing-expression detector both suites share.  Matching
is ROOT-qualified — ``np.concatenate`` concretizes, ``jnp.concatenate``
most certainly does not.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..tracecheck import rules as R
from ..tracecheck.callgraph import FunctionInfo, ModuleInfo, callee_name

# typing-constructor names that appear inside seam annotations but are
# never transportable payload classes (``List[Request]`` contributes
# ``Request``, not ``List``)
TYPING_NAMES = frozenset({
    "List", "Dict", "Tuple", "Set", "FrozenSet", "Optional", "Union",
    "Any", "Callable", "Iterable", "Iterator", "Sequence", "Mapping",
    "MutableMapping", "MutableSequence", "Type", "NamedTuple",
    "TypedDict", "Deque", "DefaultDict", "OrderedDict", "Counter",
})

# the r15 replay seams — faultcheck's FLT003 vocabulary, owned here
REPLAY_SEAM_FNS = ("_to_replay_form", "export_requests",
                   "inject_request")
SEED_REPLAY_CLASSES = frozenset({"Request"})

# exporter / adopter seam-name vocabulary: a function named with an
# EXPORT prefix detaches host state for transfer; an ADOPT prefix seats
# transferred host state.  ``_to_replay_form`` is the shared
# normalization seam both sides funnel through.
EXPORT_PREFIXES = ("export_", "harvest_", "spill_")
ADOPT_PREFIXES = ("inject_", "adopt_", "restore_")

SEED_BUNDLE_CLASSES = frozenset({"Request", "HostPage"})


def is_exporter_name(name: str) -> bool:
    return name.lstrip("_").startswith(EXPORT_PREFIXES)


def is_adopter_name(name: str) -> bool:
    return name.lstrip("_").startswith(ADOPT_PREFIXES)


def is_seam_name(name: str) -> bool:
    return (is_exporter_name(name) or is_adopter_name(name)
            or name in REPLAY_SEAM_FNS)


def seam_stem(name: str) -> str:
    """The pairing stem of a seam name: prefix stripped, singularized —
    ``export_requests``/``inject_request``/``harvest_request`` all stem
    to ``request``, so exporters and adopters of one bundle pair up."""
    tail = name.lstrip("_")
    for p in EXPORT_PREFIXES + ADOPT_PREFIXES:
        if tail.startswith(p):
            tail = tail[len(p):]
            break
    return tail.rstrip("s")


def _annotation_classes(node: ast.AST) -> Set[str]:
    """Uppercase-initial names inside one annotation expression, minus
    the typing constructors."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id[:1].isupper() and \
                sub.id not in TYPING_NAMES:
            out.add(sub.id)
    return out


def _signature_classes(fi: FunctionInfo) -> Set[str]:
    node = fi.node
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return set()
    out: Set[str] = set()
    anns = [p.annotation for p in
            (node.args.posonlyargs + node.args.args
             + node.args.kwonlyargs)]
    anns.append(node.returns)
    for ann in anns:
        if ann is not None:
            out |= _annotation_classes(ann)
    return out


def replay_class_vocabulary(modules: Dict[str, ModuleInfo]) -> frozenset:
    """Class names that flow through the replay seams: annotations on
    the parameters / returns of ``_to_replay_form``-style functions,
    plus ``Request`` itself.  This IS faultcheck FLT003's vocabulary —
    ``fault_model`` re-exports it from here."""
    names = set(SEED_REPLAY_CLASSES)
    for mod in modules.values():
        for fi in mod.functions.values():
            if fi.name in REPLAY_SEAM_FNS:
                names |= _signature_classes(fi)
    return frozenset(names)


def bundle_class_vocabulary(modules: Dict[str, ModuleInfo]) -> frozenset:
    """The full handoff vocabulary statecheck polices: the replay
    vocabulary plus ``HostPage`` and every class annotated on an
    exporter/adopter seam signature (``harvest_*``/``adopt_*``/
    ``spill_*``/``restore_*``/...)."""
    names = set(SEED_BUNDLE_CLASSES) | set(SEED_REPLAY_CLASSES)
    for mod in modules.values():
        for fi in mod.functions.values():
            if is_seam_name(fi.name):
                names |= _signature_classes(fi)
    return frozenset(names)


# ------------------------------------------------- host-purity vocabulary
# value wrappers that yield HOST values even over device inputs: their
# result is safe to store in a handoff bundle.  Builtins, numpy-rooted
# calls, host-pulling methods and jax.device_get each get their own
# list (root-qualified matching).
BUILTIN_CONCRETIZERS = frozenset({"int", "float", "bool", "str", "len",
                                  "list", "tuple", "_val"})
NP_CONCRETIZERS = frozenset({"asarray", "array", "concatenate", "copy",
                             "stack"})
HOST_METHODS = frozenset({"item", "tolist"})


def is_concretizer_call(fi: FunctionInfo, node: ast.Call) -> bool:
    name = callee_name(node)
    if name is None:
        return isinstance(node.func, ast.Attribute) and \
            node.func.attr in HOST_METHODS
    parts = name.split(".")
    tail = parts[-1]
    if tail == "device_get":
        return True                     # jax.device_get pulls to host
    if len(parts) == 1:
        return tail in BUILTIN_CONCRETIZERS
    if R._is_numpy_alias(fi, parts[0]):
        return tail in NP_CONCRETIZERS
    return tail in HOST_METHODS         # x.item() / x.tolist()


def device_producing(fi: FunctionInfo, expr: ast.expr) -> Optional[str]:
    """The jnp/lax/jax-rooted call this expression's value flows from,
    unless a concretizer (int()/np.asarray()/.item()/...) intervenes."""
    parent: dict = {}
    order: List[ast.AST] = []
    stack: List[ast.AST] = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        order.append(node)
        for child in ast.iter_child_nodes(node):
            parent[id(child)] = node
            stack.append(child)
    skipped: set = set()
    for node in order:
        if not isinstance(node, ast.Call):
            continue
        if is_concretizer_call(fi, node):
            skipped.add(id(node))
            continue
        name = callee_name(node)
        if name is None:
            continue
        if R._under_skipped(node, parent, skipped):
            continue
        root = name.split(".")[0]
        target = fi.module.module_aliases.get(root, "")
        if target in ("jax.numpy", "jax.lax", "jax") or \
                target.startswith(("jax.numpy.", "jax.lax.")) or \
                name.startswith(("jnp.", "lax.", "jax.numpy.",
                                 "jax.lax.", "jax.")):
            return name
    return None
