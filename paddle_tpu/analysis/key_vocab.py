"""Program-identity vocabulary: the ``DecodeKey.extra`` tag grammar.

jax-free on purpose (same contract as :mod:`.tile_geometry` and
:mod:`.statecheck.bundle_vocab`): this module is the ONE place the
serving stack and the keycheck lint agree on what may appear inside a
program-cache key's ``extra`` tuple.  ``generation/serving.py`` imports
these constants back when it mints keys, and keycheck's KEY006 reads
this file (by AST, at analysis time) to decide which tags are
registered — identical-by-object, so the lint and the runtime can never
drift (the tile_geometry/bundle_vocab coupling pattern; no-drift tested
from both sides).

Grammar recap (see generation/program_cache.py):

- ``extra`` is a flat tuple.  Kind-specific geometry comes FIRST
  (chunk lengths, spec-γ rungs, the ``("nlayer", (sizes...))`` tag +
  layer-group shape), then the engine-appended discriminant pairs
  ``("kv", dtype)``, ``("wt", dtype)`` and — only under tensor
  parallelism — ``("tp", N)``.
- A *tag* is the string head of a ``(tag, value)`` pair.
- An *atom* is a bare string marker (the spec-decode path/mode
  markers: ``"fused"``/``"generic"``, ``"sample"``/``"greedy"``).

New key families (tree-spec ``(rung, tree)`` programs, LoRA adapter
stacks, long-context ladders) must register their tags/atoms here —
KEY006 flags any string that appears in an ``extra`` tuple without a
registration, which is what turns "two teams invented colliding
positional tuples" into a lint error instead of a cache collision.
"""

from __future__ import annotations

# ----------------------------------------------------------- extra tags
# heads of (tag, value) pairs inside DecodeKey.extra
TAG_KV = "kv"            # ("kv", dtype)   — paged-KV element dtype
TAG_WT = "wt"            # ("wt", dtype)   — fused-decode weight-tile dtype
TAG_TP = "tp"            # ("tp", N)       — tensor-parallel degree (N > 1)
TAG_NLAYER = "nlayer"    # ("nlayer", (sizes...)) — fused layer-group shape

EXTRA_TAGS = frozenset({TAG_KV, TAG_WT, TAG_TP, TAG_NLAYER})

# ---------------------------------------------------------- extra atoms
# bare string markers (spec-decode draft program path/mode)
ATOM_FUSED = "fused"     # draft runs the fused single-block path
ATOM_GENERIC = "generic"  # draft runs the generic GSPMD path
ATOM_SAMPLE = "sample"   # draft samples (paired with top-k in the tuple)
ATOM_GREEDY = "greedy"   # draft decodes greedily

EXTRA_ATOMS = frozenset({ATOM_FUSED, ATOM_GENERIC, ATOM_SAMPLE,
                         ATOM_GREEDY})

# ------------------------------------------------- program-flag universe
# Fallback copy of flags.PROGRAM_FLAGS for analysis runs where the
# analyzed package has no flags.py (fixtures).  Against the real
# package keycheck reads flags.py's PROGRAM_FLAGS tuple by AST (the
# meshcheck _HYBRID_AXES idiom) and this set is only a safety net —
# tests/test_keycheck.py asserts the two never drift.
PROGRAM_FLAGS_FALLBACK = frozenset({
    "fused_block_decode", "fused_block_layers", "use_pallas",
    "flash_attn_min_seqlen",
    "flash_block_q", "flash_block_k", "flash_compact_stats",
    "flash_dispatch_table",
    "tpu_matmul_precision", "embedding_matmul_grad", "deterministic",
    "check_nan_inf", "check_nan_inf_level",
})

# Flags that are eager-only BY DESIGN because their value rides the key
# as a component instead of the flag tuple (the serving_kv_dtype
# annotated-exemplar shape): a traced read of one of these would be a
# KEY001 finding, but their names appearing in builder closures or
# flag reads OUTSIDE traced bodies is fine — the key discriminates.
DISCRIMINANT_FLAGS = {
    "serving_kv_dtype": TAG_KV,              # rides ("kv", dtype)
    "fused_weight_dtype": TAG_WT,            # rides ("wt", dtype)
    "serving_tp_degree": TAG_TP,             # rides ("tp", N)
    "serving_prefill_chunk": "extra[0]",     # chunk length in extra
    "serving_spec_sync_chunk": "extra[0]",   # sync-chunk length in extra
    "serving_spec_gamma": "extra[0]",        # spec rung γ in extra
}

# Engine attributes a builder MAY close over without a KEY002 finding:
# each is derivable from a key component (so two engines sharing a key
# hold equal values) or pins process-global topology the key's ("tp",N)
# pair already discriminates.
KEY_DERIVED_ATTRS = frozenset({
    "kv_dtype",          # rides ("kv", dtype)
    "weight_dtype",      # rides ("wt", dtype)
    "tp_degree",         # rides ("tp", N)
    "chunk",             # rides extra[0] of prefill_chunk keys
    "spec_sync_chunk",   # rides extra[0] of spec sync-chunk keys
    "max_batch",         # rides batch_bucket
    "_tp_mesh",          # process device set, pinned by ("tp", N)
    "_tp_axis",          # constant axis name over _tp_mesh
})

# Engine attributes that HOLD the program-flag snapshot: closing over
# one of these is the sanctioned way to thread flags into a traced
# body (the snapshot's as_tuple() is the key's flags component).
SNAPSHOT_ATTRS = frozenset({"_flags"})
