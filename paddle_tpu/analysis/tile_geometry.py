"""TPU tile/VMEM geometry shared by the fused-decode kernel, the
memwatch planner, and the kernelcheck lint (r18).

One module, three consumers, zero duplicated formulas:

- ``paddle_tpu.kernels.fused_block_decode`` imports :func:`tile` and
  :data:`LANES` (its block tiling is derived HERE, not locally);
- ``paddle_tpu.observability.memory.plan_fused_layers`` prices the
  N-layer kernel's VMEM working set by walking the template tables
  below via :func:`price_fused_decode`;
- ``paddle_tpu.analysis.kernelcheck`` (KRN002) compares the scratch
  geometry it *extracts from the kernel source* against the SAME
  templates, so the planner and the lint can never disagree: drift the
  kernel's scratch list and the lint fires; drift a template and the
  planner/lint-agreement test fires.

Deliberately dependency-free (stdlib only): the lint and the standalone
``tools/`` loaders must import this without jax installed.

Hardware constants (TPU v4/v5 class, see the accelerator guide):
vector registers are (sublane, lane) = (8, 128) f32 tiles; narrower
dtypes pack more sublanes per tile (16 for bf16, 32 for int8); VMEM is
16 MB per core and Mosaic double-buffers every *streamed* block operand
(the next grid step's block DMAs while the current one computes).
"""

from __future__ import annotations

from typing import Dict, Mapping, Sequence, Tuple

LANES = 128                       # lane count: minor-most tile dim
VMEM_LIMIT_BYTES = 16 << 20       # per-core VMEM bound
DOUBLE_BUFFER = 2                 # Mosaic's streamed-operand buffering

# minor-to-second ("sublane") tile multiple per element width
SUBLANES: Dict[str, int] = {
    "float32": 8, "f32": 8, "int32": 8, "uint32": 8,
    "bfloat16": 16, "bf16": 16, "float16": 16, "f16": 16,
    "int8": 32, "uint8": 32, "float8_e4m3fn": 32, "float8_e5m2": 32,
}

DTYPE_BYTES: Dict[str, int] = {
    "float32": 4, "f32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "bf16": 2, "float16": 2, "f16": 2,
    "int8": 1, "uint8": 1, "float8_e4m3fn": 1, "float8_e5m2": 1,
}


def tile(n: int, target: int) -> int:
    """Largest divisor of ``n`` that is <= target, preferring multiples
    of 128 (lane tiles); falls back to any divisor so odd dims stay
    correct (just less efficient)."""
    if n <= target:
        return n
    for cand in range(target - target % 128, 0, -128):
        if n % cand == 0:
            return cand
    for cand in range(min(target, n), 0, -1):
        if n % cand == 0:
            return cand
    return n


def sublane_multiple(dtype_name: str) -> int:
    """Required second-minor tile multiple for a dtype ('' unknown -> 0,
    meaning: no static claim)."""
    return SUBLANES.get(dtype_name.rsplit(".", 1)[-1], 0)


# --------------------------------------------------------- templates
# Symbolic shape templates of ``fused_multi_block_decode_pallas``.
# Every entry is a tuple of symbol names resolved against the dict
# :func:`fused_decode_env` builds; integer literals spell themselves.
# KRN002 normalizes the shapes it extracts from the kernel source to
# exactly these symbol spellings before comparing.

# streamed block operands (double-buffered by Mosaic)
FUSED_DECODE_WEIGHT_STREAM: Tuple[Tuple[str, ...], ...] = (
    ("1", "hidden"),            # ln1
    ("1", "hidden"),            # ln2
    ("tr_h", "tc_qkv"),         # wqkv tile
    ("tr_o", "tc_o"),           # wo tile
    ("tr_h", "tc_f"),           # wgu gate tile
    ("tr_h", "tc_f"),           # wgu up tile
    ("tr_i", "tc_d"),           # wd tile
)
# const-mapped activation in/out blocks (still double-buffered)
FUSED_DECODE_ACTIVATION_IO: Tuple[Tuple[str, ...], ...] = (
    ("b_pad", "hidden"),        # x in
    ("b_pad", "hidden"),        # out
    ("b_pad", "d"),             # sin
    ("b_pad", "d"),             # cos
    ("b_pad", "kvw"),           # k_new
    ("b_pad", "kvw"),           # v_new
)
# per-layer K/V page blocks (2 operands per grouped layer — the only
# term that scales with the fused-layer count N)
FUSED_DECODE_KV_BLOCK: Tuple[Tuple[str, ...], ...] = (
    ("1", "1", "page_size", "d"),
    ("1", "1", "page_size", "d"),
)
# persistent f32 VMEM scratch of the N-layer kernel — the multiset
# KRN002 checks the extracted ``scratch_shapes`` against
FUSED_DECODE_SCRATCH: Tuple[Tuple[str, ...], ...] = (
    ("b_pad", "hidden"),        # x carry
    ("b_pad", "hidden"),        # h (normed)
    ("b_pad", "wq_cols"),       # merged qkv
    ("b_pad", "qw"),            # attn out
    ("b_pad", "hidden"),        # x2 (residual)
    ("b_pad", "inter"),         # silu(g)*u
    ("b_pad", "tc_max"),        # acc a
    ("b_pad", "tc_max"),        # acc b
    ("rep_pad", "d"),           # attn acc
    ("rep_pad", "LANES"),       # attn m
    ("rep_pad", "LANES"),       # attn l
)
# the single-layer kernel's scratch (``fused_block_decode_pallas``):
# same carries plus split q/k/v projections instead of the merged one
FUSED_DECODE_SINGLE_SCRATCH: Tuple[Tuple[str, ...], ...] = (
    ("b_pad", "hidden"),        # h (normed)
    ("b_pad", "qw"),            # q
    ("b_pad", "kvw"),           # k_new
    ("b_pad", "kvw"),           # v_new
    ("b_pad", "qw"),            # attn out
    ("b_pad", "hidden"),        # x2 (residual)
    ("b_pad", "inter"),         # silu(g)*u
    ("b_pad", "tc_max"),        # acc a
    ("b_pad", "tc_max"),        # acc b
    ("rep_pad", "d"),           # attn acc
    ("rep_pad", "LANES"),       # attn m
    ("rep_pad", "LANES"),       # attn l
)


def fused_decode_env(*, hidden: int, intermediate: int, heads: int,
                     kv_heads: int, head_dim: int, batch: int = 8,
                     page_size: int = 64) -> Dict[str, int]:
    """The symbol environment both the kernel and the planner tile
    from: every template symbol above resolves against this dict."""
    d = int(head_dim)
    rep = int(heads) // int(kv_heads)
    qw = int(heads) * d
    kvw = int(kv_heads) * d
    wq_cols = qw + 2 * kvw
    return {
        "hidden": int(hidden), "inter": int(intermediate), "d": d,
        "qw": qw, "kvw": kvw, "wq_cols": wq_cols,
        "b_pad": -(-int(batch) // 8) * 8,
        "rep_pad": -(-rep // 8) * 8,
        "tr_h": tile(int(hidden), 512),
        "tr_o": tile(qw, 512),
        "tr_i": tile(int(intermediate), 512),
        "tc_qkv": tile(wq_cols, 256),
        "tc_o": tile(int(hidden), 256),
        "tc_f": tile(int(intermediate), 256),
        "tc_d": tile(int(hidden), 256),
        "page_size": int(page_size),
        "LANES": LANES,
    }


def _finish_env(env: Dict[str, int]) -> Dict[str, int]:
    env = dict(env)
    env["tc_max"] = max(env["tc_qkv"], env["tc_o"], env["tc_f"],
                        env["tc_d"])
    return env


def template_elems(shapes: Sequence[Tuple[str, ...]],
                   env: Mapping[str, int]) -> int:
    """Total element count of a template table under ``env``."""
    total = 0
    for shape in shapes:
        n = 1
        for sym in shape:
            n *= int(sym) if sym.isdigit() else env[sym]
        total += n
    return total


def price_fused_decode(env: Mapping[str, int], *, fused_layers: int,
                       io_dtype_bytes: int = 2,
                       vmem_limit: int = VMEM_LIMIT_BYTES
                       ) -> Dict[str, int]:
    """Price the N-layer fused decode kernel's VMEM working set from
    the templates.  Streamed blocks (weights, activations, the
    per-layer page blocks) pay the Mosaic double-buffer factor at the
    streamed storage width; scratch is persistent f32."""
    n = int(fused_layers)
    if n < 1:
        raise ValueError(f"fused_layers must be >= 1, got {n}")
    env = _finish_env(dict(env))
    io = int(io_dtype_bytes)
    weight_stream = DOUBLE_BUFFER * io * template_elems(
        FUSED_DECODE_WEIGHT_STREAM, env)
    activation_io = DOUBLE_BUFFER * io * template_elems(
        FUSED_DECODE_ACTIVATION_IO, env)
    kv_page = DOUBLE_BUFFER * io * n * template_elems(
        FUSED_DECODE_KV_BLOCK, env)
    scratch = DTYPE_BYTES["float32"] * template_elems(
        FUSED_DECODE_SCRATCH, env)
    total = weight_stream + activation_io + kv_page + scratch
    return {
        "weight_stream_buffers": weight_stream,
        "activation_io_buffers": activation_io,
        "kv_page_buffers": kv_page,
        "scratch": scratch,
        "total": int(total),
        "vmem_limit": int(vmem_limit),
        "fits": total <= int(vmem_limit),
        "headroom_bytes": int(vmem_limit) - int(total),
    }
