"""tracecheck — a JAX trace-discipline static analyzer.

The bug classes that actually bit this repo are not numeric — they are
*trace-discipline* bugs that runtime sanitizers see only after the fact:

- per-call registry flag reads baked into traced programs (the class
  ``flags.snapshot()`` fixed by hand in r06),
- host syncs silently defeating the async ``Model.fit`` / serving loops,
- donated-buffer reuse around ``jax.jit(..., donate_argnums=...)``,
- fresh-closure jit admissions retracing per call (the class
  ``generation/program_cache.py`` exists to prevent),
- wall-clock / stdlib RNG evaluated once at trace time,
- Python control flow on tensor values inside jitted code.

``tracecheck`` parses the package (AST only — nothing is imported or
executed), builds a traced-reachability call graph over functions handed
to ``jax.jit`` / ``pl.pallas_call`` / ``jax.checkpoint`` / ``shard_map``
/ ``lax`` control flow and the repo's own wrappers (``apply_op``, the
decode program cache, ``TrainStep``), and applies the TRC rules to code
reachable under trace.  Findings support inline
``# tracecheck: disable=TRC00x`` pragmas and a checked-in baseline so
legacy findings never block; the tier-1 test gates NEW findings only.

Run it locally::

    python tools/tracecheck.py paddle_tpu
    python tools/tracecheck.py paddle_tpu --json
    python tools/tracecheck.py paddle_tpu --update-baseline
"""

from .findings import (Finding, RULES, fingerprint, load_baseline,
                       subtract_baseline, write_baseline)
from .analyzer import (AnalyzerConfig, ParsedPackage, analyze_package,
                       parse_package)

__all__ = [
    "AnalyzerConfig", "Finding", "ParsedPackage", "RULES",
    "analyze_package", "fingerprint", "load_baseline", "parse_package",
    "subtract_baseline", "write_baseline",
]
