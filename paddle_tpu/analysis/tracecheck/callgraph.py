"""Traced-reachability call graph over a package (pure AST, no imports).

Two questions drive every rule:

1. **Which functions run under a jax trace?**  Roots are functions
   handed to (or decorated with) a *trace wrapper* — ``jax.jit``,
   ``pl.pallas_call``, ``jax.checkpoint``/``remat``, ``shard_map``,
   ``jax.vmap``/``grad``/``value_and_grad``, ``jax.custom_vjp``/``jvp``,
   ``lax`` control flow, and the repo's own wrappers (``apply_op``,
   ``jit_fn``/``to_static``) — plus every function in configured
   *traced modules* (the op/kernel libraries whose documented contract
   is "callable under jit").  Reachability closes over statically
   resolvable calls: locals in scope, module-level defs, ``from x
   import f`` edges inside the package, ``mod.f`` through an in-package
   module alias, and ``self.m`` within a class.

2. **Which callables donate buffers?**  ``jax.jit(f, donate_argnums=
   (..,))`` results are *donors*; donor-ness propagates through local /
   ``self.`` assignment, ``functools.partial``, function return values,
   and the decode-program-cache admission idiom ``cache.get(key,
   builder)`` (the compiled step a builder returns).  Rule TRC003
   consumes the resulting map of call-site -> donated positions.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# trace wrappers: name -> positions of the traced callable argument(s)
# (None = every positional argument may be a traced callable)
_JAX_WRAPPERS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,), "pallas_call": (0,), "checkpoint": (0,), "remat": (0,),
    "shard_map": (0,), "vmap": (0,), "pmap": (0,), "grad": (0,),
    "value_and_grad": (0,), "custom_vjp": (0,), "custom_jvp": (0,),
    "named_call": (0,),
    # lax control flow — bodies are traced (matched only under a `lax`
    # root, see _LAX_ONLY: `jax.tree.map` / builtin map must not hit)
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,),
    "cond": (1, 2), "switch": (1, 2, 3, 4, 5, 6, 7, 8),
    "associative_scan": (0,), "map": (0,),
}
_LAX_ONLY = {"scan", "while_loop", "fori_loop", "cond", "switch",
             "associative_scan", "map"}
# repo wrappers
_REPO_WRAPPERS: Dict[str, Tuple[int, ...]] = {
    "apply_op": (1,),          # apply_op(name, fn, *args)
    "jit_fn": (0,),
    "to_static": (0,),
}


@dataclass
class FunctionInfo:
    qualname: str                       # module-relative ('Cls.m', 'f.g')
    node: ast.AST                       # FunctionDef / Lambda
    module: "ModuleInfo"
    parent: Optional["FunctionInfo"]    # lexically enclosing function
    cls: Optional[str]                  # enclosing class name, if a method
    lineno: int = 0
    traced: bool = False
    trace_root: bool = False
    hotpath: bool = False
    calls: List[ast.Call] = field(default_factory=list)
    # donor analysis results filled by DonorPass
    returns_donor: Optional[Tuple[int, ...]] = None

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


@dataclass
class ModuleInfo:
    relpath: str                        # posix, relative to package parent
    tree: ast.Module
    source_lines: List[str]
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    # import alias tables
    module_aliases: Dict[str, str] = field(default_factory=dict)   # name->modpath
    imported_names: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    # ^ local name -> (module path, original name) for `from X import Y`
    lambda_seq: int = 0

    def line(self, n: int) -> str:
        if 1 <= n <= len(self.source_lines):
            return self.source_lines[n - 1].strip()
        return ""


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def callee_name(call: ast.Call) -> Optional[str]:
    return _dotted(call.func)


def wrapper_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """If ``call`` invokes a trace wrapper, the positional indices whose
    arguments are traced callables; else None.  Matches on the terminal
    attribute name so every alias spelling (``jax.jit``, ``jit``,
    ``pl.pallas_call``, ``jax.experimental.shard_map.shard_map``,
    ``functools.partial(jax.jit, ...)`` as decorator) resolves."""
    name = callee_name(call)
    if name is None:
        return None
    tail = name.rsplit(".", 1)[-1]
    if tail == "partial" and call.args:
        inner = _dotted(call.args[0])
        if inner is not None:
            itail = inner.rsplit(".", 1)[-1]
            if itail in _JAX_WRAPPERS or itail in _REPO_WRAPPERS:
                # partial(jax.jit, f?) — shift positions by the bound args
                base = _JAX_WRAPPERS.get(itail, _REPO_WRAPPERS.get(itail))
                return tuple(p - (len(call.args) - 1) for p in base
                             if p - (len(call.args) - 1) >= 0) or (0,)
        return None
    if tail in _LAX_ONLY:
        parts = name.split(".")
        return _JAX_WRAPPERS[tail] if "lax" in parts[:-1] else None
    if tail in _JAX_WRAPPERS:
        return _JAX_WRAPPERS[tail]
    if tail in _REPO_WRAPPERS:
        return _REPO_WRAPPERS[tail]
    return None


def is_wrapper_decorator(dec: ast.expr) -> bool:
    """Decorator forms that put the function body under trace:
    ``@jax.jit``, ``@jit_fn``, ``@jax.custom_vjp``,
    ``@functools.partial(jax.jit, static_argnums=..)``, ``@checkpoint``.
    """
    if isinstance(dec, ast.Call):
        name = callee_name(dec)
        if name is None:
            return False
        tail = name.rsplit(".", 1)[-1]
        if tail == "partial" and dec.args:
            inner = _dotted(dec.args[0])
            if inner is not None and \
                    inner.rsplit(".", 1)[-1] in _JAX_WRAPPERS:
                return True
        return tail in _JAX_WRAPPERS or tail in _REPO_WRAPPERS
    name = _dotted(dec)
    if name is None:
        return False
    tail = name.rsplit(".", 1)[-1]
    return tail in _JAX_WRAPPERS or tail in _REPO_WRAPPERS


# -------------------------------------------------------------- indexing
class _Indexer(ast.NodeVisitor):
    """One pass per module: functions (incl. nested + lambdas), imports,
    per-function call lists.  Nested defs do NOT contribute their body
    statements to the parent's rule scan — each FunctionInfo is analyzed
    against its own traced flag."""

    def __init__(self, mod: ModuleInfo, package: str):
        self.mod = mod
        self.package = package
        self.stack: List[FunctionInfo] = []
        self.cls_stack: List[str] = []

    # imports ------------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.mod.module_aliases[a.asname or a.name.split(".")[0]] = \
                a.name
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = self._resolve_from(node)
        for a in node.names:
            local = a.asname or a.name
            # `from X import Y`: Y may be a submodule or a symbol; record
            # both interpretations, resolution tries symbol first
            self.mod.imported_names[local] = (base, a.name)
            self.mod.module_aliases.setdefault(local, f"{base}.{a.name}")
        self.generic_visit(node)

    def _resolve_from(self, node: ast.ImportFrom) -> str:
        if node.level == 0:
            return node.module or ""
        # relative: anchor at this module's package path
        parts = self.mod.relpath[:-3].split("/")          # strip .py
        if parts[-1] == "__init__":
            parts = parts[:-1]
        else:
            parts = parts[:-1]
        # one level = current package; each extra level pops one
        for _ in range(node.level - 1):
            if parts:
                parts = parts[:-1]
        base = ".".join(parts)
        if node.module:
            base = f"{base}.{node.module}" if base else node.module
        return base

    # classes / functions ------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls_stack.append(node.name)
        for child in node.body:
            self.visit(child)
        self.cls_stack.pop()

    def _enter_function(self, node, name: str) -> FunctionInfo:
        parent = self.stack[-1] if self.stack else None
        prefix = parent.qualname + "." if parent else (
            ".".join(self.cls_stack) + "." if self.cls_stack else "")
        info = FunctionInfo(
            qualname=prefix + name, node=node, module=self.mod,
            parent=parent, cls=self.cls_stack[-1] if self.cls_stack else None,
            lineno=getattr(node, "lineno", 0))
        self.mod.functions[info.qualname] = info
        return info

    def _walk_function(self, info: FunctionInfo, body) -> None:
        self.stack.append(info)
        for child in body:
            self.visit(child)
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        info = self._enter_function(node, node.name)
        for dec in node.decorator_list:
            self.visit(dec)
            if is_wrapper_decorator(dec):
                info.trace_root = True
        self._walk_function(info, node.body)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.mod.lambda_seq += 1
        info = self._enter_function(
            node, f"<lambda:{node.lineno}:{self.mod.lambda_seq}>")
        self._walk_function(info, [ast.Expr(value=node.body)])

    # calls --------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        if self.stack:
            self.stack[-1].calls.append(node)
        else:
            self.mod.functions.setdefault(
                "", FunctionInfo("", self.mod.tree, self.mod, None, None)
            ).calls.append(node)
        self.generic_visit(node)


# ------------------------------------------------------------ call graph
class CallGraph:
    def __init__(self, modules: Dict[str, ModuleInfo], package: str):
        self.modules = modules
        self.package = package
        # (modpath, funcname) -> [FunctionInfo] for module-level defs
        self.by_module_name: Dict[Tuple[str, str], List[FunctionInfo]] = {}
        # class method index: (modpath, clsname, methname) -> FunctionInfo
        self.methods: Dict[Tuple[str, str, str], FunctionInfo] = {}
        for mp, mod in modules.items():
            for qn, fi in mod.functions.items():
                if not qn:
                    continue
                parts = qn.split(".")
                if len(parts) == 1:
                    self.by_module_name.setdefault((mp, parts[0]), []) \
                        .append(fi)
                elif fi.cls is not None and len(parts) == 2:
                    self.methods[(mp, fi.cls, parts[1])] = fi
                    # methods are also name-resolvable within the module
                    self.by_module_name.setdefault((mp, parts[-1]), []) \
                        .append(fi)

    def modpath_of(self, mod: ModuleInfo) -> str:
        p = mod.relpath[:-3]
        if p.endswith("/__init__"):
            p = p[: -len("/__init__")]
        return p.replace("/", ".")

    # resolution ---------------------------------------------------------
    def resolve_call(self, fi: FunctionInfo, call: ast.Call
                     ) -> List[FunctionInfo]:
        name = callee_name(call)
        if name is None:
            return []
        mod = fi.module
        mp = self.modpath_of(mod)
        parts = name.split(".")

        # self.m(...): method on the enclosing class
        if parts[0] in ("self", "cls") and len(parts) == 2 and fi.cls:
            hit = self.methods.get((mp, fi.cls, parts[1]))
            return [hit] if hit else []

        if len(parts) == 1:
            n = parts[0]
            # nested function in an enclosing scope
            scope = fi
            while scope is not None:
                hit = mod.functions.get(
                    (scope.qualname + "." if scope.qualname else "") + n)
                if hit is not None:
                    return [hit]
                scope = scope.parent
            # module-level def (incl. methods indexed by bare name only
            # when unambiguous is too risky — restrict to plain defs)
            hits = [f for f in self.by_module_name.get((mp, n), [])
                    if f.cls is None]
            if hits:
                return hits
            # from X import n
            imp = mod.imported_names.get(n)
            if imp is not None:
                return self._resolve_imported(imp[0], imp[1])
            return []

        # mod_alias.func(...)
        alias, rest = parts[0], parts[1:]
        target_mod = mod.module_aliases.get(alias)
        if target_mod is None:
            imp = mod.imported_names.get(alias)
            if imp is not None:
                target_mod = f"{imp[0]}.{imp[1]}" if imp[0] else imp[1]
        if target_mod is None or not target_mod.startswith(self.package):
            return []
        if len(rest) == 1:
            return self._resolve_imported(target_mod, rest[0])
        return []

    def _resolve_imported(self, modpath: str, name: str
                          ) -> List[FunctionInfo]:
        if not modpath or not modpath.startswith(self.package):
            return []
        # exact module file
        hits = [f for f in self.by_module_name.get((modpath, name), [])
                if f.cls is None]
        if hits:
            return hits
        # re-export through a package __init__: search submodules
        prefix = modpath + "."
        out: List[FunctionInfo] = []
        for (mp, n), fis in self.by_module_name.items():
            if n == name and mp.startswith(prefix):
                out.extend(f for f in fis if f.cls is None)
        return out

    # reachability -------------------------------------------------------
    def propagate_traced(self) -> None:
        work: List[FunctionInfo] = []
        for mod in self.modules.values():
            for fi in mod.functions.values():
                if fi.trace_root and not fi.traced:
                    fi.traced = True
                    work.append(fi)
        while work:
            fi = work.pop()
            for call in fi.calls:
                for callee in self.resolve_call(fi, call):
                    if not callee.traced:
                        callee.traced = True
                        work.append(callee)


def index_module(relpath: str, source: str, package: str) -> ModuleInfo:
    tree = ast.parse(source)
    mod = ModuleInfo(relpath=relpath, tree=tree,
                     source_lines=source.splitlines())
    _Indexer(mod, package).visit(tree)
    return mod


def mark_roots_from_wrapper_calls(mod: ModuleInfo) -> None:
    """Functions *passed to* trace wrappers anywhere in the module become
    roots: ``jax.jit(run)``, ``pl.pallas_call(kernel, ...)``,
    ``lax.scan(body, ..)``, ``apply_op("x", fn, ..)``, lambdas inline."""
    lambda_by_pos = {
        (f.node.lineno, f.node.col_offset): f
        for f in mod.functions.values()
        if isinstance(f.node, ast.Lambda)}

    def local_named(fi_scope: Optional[FunctionInfo], n: str):
        scope = fi_scope
        while scope is not None:
            hit = mod.functions.get(scope.qualname + "." + n)
            if hit is not None:
                return hit
            scope = scope.parent
        return mod.functions.get(n)

    for owner in list(mod.functions.values()):
        for call in owner.calls:
            pos = wrapper_positions(call)
            if pos is None:
                continue
            for p in pos:
                if p >= len(call.args):
                    continue
                arg = call.args[p]
                if isinstance(arg, ast.Lambda):
                    hit = lambda_by_pos.get((arg.lineno, arg.col_offset))
                    if hit:
                        hit.trace_root = True
                elif isinstance(arg, ast.Name):
                    hit = local_named(owner if owner.qualname else None,
                                      arg.id)
                    if hit is not None:
                        hit.trace_root = True
                elif isinstance(arg, ast.Call):
                    # jax.jit(functools.partial(f, ...)) — unwrap partial
                    n = callee_name(arg)
                    if n and n.rsplit(".", 1)[-1] == "partial" and arg.args:
                        inner = arg.args[0]
                        if isinstance(inner, ast.Name):
                            hit = local_named(
                                owner if owner.qualname else None,
                                inner.id)
                            if hit is not None:
                                hit.trace_root = True
