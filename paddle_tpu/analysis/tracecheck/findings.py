"""Finding model, pragma suppression, and the checked-in baseline.

A finding's *fingerprint* deliberately excludes the line number — it is
``rule:path:function:stripped-source-text`` — so reformatting or adding
code above a legacy finding does not churn the baseline.  The baseline
is a multiset of fingerprints (equal lines in one function count), kept
as a sorted JSON list so ``--update-baseline`` round-trips byte-stable.
"""

from __future__ import annotations

import json
import re
from collections import Counter
from dataclasses import dataclass
from typing import Dict, Iterable, List, Tuple

# ---------------------------------------------------------------- rules
RULES: Dict[str, str] = {
    "TRC001": "registry flag read under trace — resolve a flags.snapshot() "
              "at the trace boundary and thread it through (per-call "
              "get_flag values are baked in at trace time and bypass the "
              "program-cache flag key)",
    "TRC002": "host synchronization on a traced/async value in a traced "
              "function or declared hot path (float()/.item()/.numpy()/"
              "np.asarray()/block_until_ready() stalls the dispatch "
              "pipeline or fails under trace)",
    "TRC003": "donated-buffer discipline around jax.jit(donate_argnums=...) "
              "— a donated argument may not be read after dispatch, and a "
              "donated view of live object state must be detached first "
              "(take_*/donate_* ownership handoff)",
    "TRC004": "unstable jit admission — jax.jit of a fresh closure/lambda "
              "or inside a loop retraces per call; hoist it or key it "
              "through a program cache",
    "TRC005": "wall-clock or stdlib/numpy RNG under trace — evaluated once "
              "at trace time and baked into the program; use traced "
              "jax.random keys / pass times in as arguments",
    "TRC006": "Python if/while on a tensor-valued expression in traced "
              "code — raises TracerBoolConversionError or silently "
              "specializes; use lax.cond/jnp.where (guard eager-only "
              "branches with isinstance(x, Tracer))",
    "TRC007": "telemetry write (observability registry/span tracer) in "
              "trace-reachable code — host-side only, a write under "
              "trace fires once at trace time or fails on a tracer; in "
              "declared hotpath code the write is legal but must carry "
              "an explicit pragma with a reason (per-step host cost)",
}

def pragma_re(tool: str = "tracecheck") -> "re.Pattern":
    """The inline-pragma pattern for one analyzer.  The machinery below is
    shared with meshcheck (``# meshcheck: disable=MSH00x``); each suite
    recognizes only its own tool prefix so a pragma never silences the
    other suite's rules."""
    return re.compile(
        r"#\s*" + re.escape(tool) +
        r":\s*(disable|hotpath)(?:=([A-Za-z0-9_,\s]+))?")


_PRAGMA_RE = pragma_re("tracecheck")


@dataclass(frozen=True)
class Finding:
    rule: str                 # TRC00x
    path: str                 # repo-relative posix path
    line: int                 # 1-based
    func: str                 # module-relative qualname ('' = module scope)
    message: str
    source: str = ""          # stripped source of the offending line

    def format(self) -> str:
        where = f" [{self.func}]" if self.func else ""
        return f"{self.path}:{self.line}: {self.rule}{where}: {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "func": self.func, "message": self.message,
                "source": self.source, "fingerprint": fingerprint(self)}


def fingerprint(f: Finding) -> str:
    return f"{f.rule}:{f.path}:{f.func}:{f.source}"


def dedupe_findings(findings: List[Finding]) -> List[Finding]:
    """Sorted, exact-duplicate-free finding list (a call site can be
    visited via overlapping scans) — the one finalization both suites
    share, so their ordering/dedup semantics can never drift."""
    seen = set()
    uniq: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule,
                                             f.func)):
        key = (f.rule, f.path, f.line, f.func, f.message)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq


# -------------------------------------------------------------- pragmas
def parse_pragmas(source_lines: List[str],
                  tool: str = "tracecheck") -> Dict[int, set]:
    """Line -> set of disabled rule codes (empty set = all rules).
    A pragma applies to its own line and, when the line holds nothing
    else (a standalone comment), to the following line."""
    out: Dict[int, set] = {}
    pat = _PRAGMA_RE if tool == "tracecheck" else pragma_re(tool)

    def add(line: int, codes: set) -> None:
        cur = out.get(line)
        if cur is None:
            out[line] = set(codes)
        elif not cur or not codes:
            out[line] = set()       # blanket disable absorbs everything
        else:
            cur.update(codes)

    for i, text in enumerate(source_lines, start=1):
        m = pat.search(text)
        if not m or m.group(1) != "disable":
            continue
        codes = (set(c.strip().upper() for c in m.group(2).split(",")
                     if c.strip()) if m.group(2) else set())
        add(i, codes)
        if text.strip().startswith("#"):
            add(i + 1, codes)
    return out


def hotpath_lines(source_lines: List[str]) -> set:
    """Lines carrying a ``# tracecheck: hotpath`` marker (the marker on a
    ``def`` line — or the standalone comment line right above it —
    declares that function a latency hot path for TRC002)."""
    marked = set()
    for i, text in enumerate(source_lines, start=1):
        m = _PRAGMA_RE.search(text)
        if m and m.group(1) == "hotpath":
            marked.add(i)
            if text.strip().startswith("#"):
                marked.add(i + 1)
    return marked


def suppressed(f: Finding, pragmas: Dict[int, set]) -> bool:
    codes = pragmas.get(f.line)
    if codes is None:
        return False
    return not codes or f.rule in codes


# ------------------------------------------------------------- baseline
def load_baseline(path) -> Counter:
    try:
        with open(path) as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return Counter()
    if isinstance(data, dict):           # {"findings": [...]} envelope
        data = data.get("findings", [])
    return Counter(str(e) for e in data)


def write_baseline(path, findings: Iterable[Finding]) -> List[str]:
    entries = sorted(fingerprint(f) for f in findings)
    with open(path, "w") as fh:
        json.dump(entries, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return entries


def subtract_baseline(findings: List[Finding], baseline: Counter
                      ) -> Tuple[List[Finding], Counter]:
    """Split into (new findings, unmatched-baseline leftovers). Multiset
    semantics: N baselined copies of one fingerprint absorb N findings."""
    budget = Counter(baseline)
    new: List[Finding] = []
    for f in findings:
        fp = fingerprint(f)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            new.append(f)
    leftovers = Counter({k: v for k, v in budget.items() if v > 0})
    return new, leftovers
