"""Donor analysis: which call sites dispatch through
``jax.jit(..., donate_argnums=...)`` and at which positions.

Module-scoped fixpoint (the repo keeps builders and their call sites in
one module — serving.py, train_step.py, pipeline_parallel.py,
incubate/nn/functional.py).  Donor-ness propagates through:

- ``x = jax.jit(f, donate_argnums=(..))``          (local / module name)
- ``self.x = jax.jit(...)``                        (class attribute)
- ``return jax.jit(...)``                          (returns-donor fn)
- ``functools.partial(F, ...)`` of a returns-donor F (calling the
  partial yields the donor)
- ``cache.get(key, builder)`` where the builder (name or partial) is
  returns-donor — the decode-program-cache admission idiom: ``get``
  returns the compiled step the builder built.

Positions are "may donate": ``donate_argnums=(0, 1) if donate else ()``
contributes {0, 1}.  A donated position that cannot be proven constant
is dropped (under-reporting beats false alarms in a tier-1 gate).
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Tuple

from .callgraph import FunctionInfo, ModuleInfo, callee_name, _dotted


def _const_positions(node: ast.AST) -> Tuple[int, ...]:
    """Every integer constant anywhere in the expression — handles
    ``(3,)``, ``(0, 1) if donate else ()`` and plain ``0``."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, int) \
                and not isinstance(sub.value, bool):
            out.append(sub.value)
    return tuple(sorted(set(out)))


class ModuleDonors:
    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        # (owner-func qualname or '', local name) -> positions
        self.named: Dict[Tuple[str, str], Tuple[int, ...]] = {}
        # attribute chain ('self._jit_step') per class -> positions
        self.attrs: Dict[Tuple[str, str], Tuple[int, ...]] = {}
        self._compute()

    # -------------------------------------------------------- donor exprs
    def _jit_donate_positions(self, node: ast.AST,
                              owner: FunctionInfo) -> Optional[Tuple[int, ...]]:
        """Positions if ``node`` evaluates to a donating jitted callable."""
        if not isinstance(node, ast.Call):
            return None
        name = callee_name(node)
        if name is not None:
            tail = name.rsplit(".", 1)[-1]
        elif isinstance(node.func, ast.Attribute):
            # Call-rooted chain, e.g. decode_program_cache().get(...)
            tail = node.func.attr
        else:
            return None
        if tail in ("jit", "jit_fn"):
            for kw in node.keywords:
                if kw.arg == "donate_argnums":
                    pos = self._positions_of_value(kw.value, owner)
                    return pos or None
            return None
        # cache.get(key, builder) — admission wrapper returning the
        # builder's compiled step
        if tail == "get" and len(node.args) >= 2:
            rd = self._returns_donor_of(node.args[1], owner)
            if rd:
                return rd
            return None
        # call of a returns-donor function: fn = self._prefill_program()
        rd = self._callable_returns_donor(node.func, owner)
        return rd

    def _positions_of_value(self, value: ast.AST,
                            owner: FunctionInfo) -> Tuple[int, ...]:
        pos = _const_positions(value)
        if pos:
            return pos
        # donate_argnums bound to a local name earlier in the function
        if isinstance(value, ast.Name) and owner is not None and \
                not isinstance(owner.node, (ast.Module, ast.Lambda)):
            for stmt in ast.walk(owner.node):
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and t.id == value.id:
                            pos = _const_positions(stmt.value)
                            if pos:
                                return pos
        return ()

    def _callable_returns_donor(self, func: ast.AST,
                                owner: Optional[FunctionInfo]
                                ) -> Optional[Tuple[int, ...]]:
        """Does CALLING this expression yield a donor?  (the expression
        names a returns-donor function/method)"""
        chain = _dotted(func)
        if chain is None:
            return None
        parts = chain.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2 and owner and \
                owner.cls:
            m = self.mod.functions.get(f"{owner.cls}.{parts[1]}")
            if m is not None and m.returns_donor:
                return m.returns_donor
            return None
        if len(parts) == 1:
            f = self._lookup_function(parts[0], owner)
            if f is not None and f.returns_donor:
                return f.returns_donor
        return None

    def _returns_donor_of(self, node: ast.AST,
                          owner: Optional[FunctionInfo]
                          ) -> Optional[Tuple[int, ...]]:
        """Value that, when called, returns a donor: a returns-donor
        function name, or functools.partial of one."""
        if isinstance(node, ast.Name):
            f = self._lookup_function(node.id, owner)
            if f is not None and f.returns_donor:
                return f.returns_donor
            # a local bound to a partial/builder earlier in the function:
            #   builder = functools.partial(_build_x, ...); cache.get(k, builder)
            if owner is not None and not isinstance(
                    owner.node, (ast.Module, ast.Lambda)):
                hit = None
                for stmt in ast.walk(owner.node):
                    if isinstance(stmt, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id == node.id
                            for t in stmt.targets):
                        rd = (None if stmt.value is node else
                              self._returns_donor_of(stmt.value, owner))
                        hit = rd if rd else hit
                return hit
            return None
        if isinstance(node, ast.Call):
            name = callee_name(node)
            if name and name.rsplit(".", 1)[-1] == "partial" and node.args:
                return self._returns_donor_of(node.args[0], owner)
        return None

    def _lookup_function(self, name: str, owner: Optional[FunctionInfo]
                         ) -> Optional[FunctionInfo]:
        scope = owner
        while scope is not None:
            hit = self.mod.functions.get(scope.qualname + "." + name)
            if hit is not None:
                return hit
            scope = scope.parent
        return self.mod.functions.get(name)

    # ------------------------------------------------------------ fixpoint
    def _compute(self) -> None:
        for _ in range(4):                      # donor chains are short
            changed = False
            for fi in list(self.mod.functions.values()):
                if isinstance(fi.node, (ast.Module, ast.Lambda)):
                    continue
                for stmt in ast.walk(fi.node):
                    if isinstance(stmt, ast.Assign):
                        pos = self._jit_donate_positions(stmt.value, fi)
                        if not pos:
                            continue
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                key = (fi.qualname, t.id)
                                if self.named.get(key) != pos:
                                    self.named[key] = pos
                                    changed = True
                            else:
                                chain = _dotted(t)
                                if chain and chain.startswith("self.") \
                                        and fi.cls:
                                    key = (fi.cls, chain)
                                    if self.attrs.get(key) != pos:
                                        self.attrs[key] = pos
                                        changed = True
                    elif isinstance(stmt, ast.Return) and \
                            stmt.value is not None:
                        pos = self._jit_donate_positions(stmt.value, fi)
                        if pos is None:
                            # `return self._prefill_fn` where the attr
                            # was assigned a donor in this class, or
                            # `return fn` of a local bound to a donor
                            # earlier in the function (the per-rung
                            # program-dict idiom: fn = cache.get(...);
                            # self._fns[bucket] = fn; return fn)
                            chain = _dotted(stmt.value)
                            if chain and fi.cls:
                                pos = self.attrs.get((fi.cls, chain))
                            if not pos and chain and "." not in chain:
                                pos = self.named.get((fi.qualname, chain))
                        if pos and fi.returns_donor != pos:
                            fi.returns_donor = pos
                            changed = True
            if not changed:
                break
        # module-level assignments (rare): STEP = jax.jit(...)
        for stmt in self.mod.tree.body:
            if isinstance(stmt, ast.Assign):
                pos = self._jit_donate_positions(stmt.value, None)
                if pos:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.named[("", t.id)] = pos

    # ------------------------------------------------------------ resolver
    def donated_positions(self, fi: FunctionInfo, call: ast.Call
                          ) -> Optional[Tuple[int, ...]]:
        chain = _dotted(call.func)
        if chain is None:
            return None
        parts = chain.split(".")
        if len(parts) == 1:
            scope = fi
            while scope is not None:
                pos = self.named.get((scope.qualname, parts[0]))
                if pos:
                    return pos
                scope = scope.parent
            return self.named.get(("", parts[0]))
        if parts[0] in ("self", "cls") and fi.cls:
            return self.attrs.get((fi.cls, chain))
        return None
