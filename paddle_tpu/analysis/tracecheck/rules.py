"""The TRC rule checkers.

Each rule is a function ``(FunctionInfo, CallGraph) -> List[Finding]``
run over ONE function body (nested defs are their own FunctionInfo, so
visitors never descend into an inner ``def``/``lambda`` — the inner
function is judged against its own traced flag).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence

from .callgraph import CallGraph, FunctionInfo, _dotted, callee_name
from .findings import Finding

# ownership-handoff naming convention TRC003 recognizes: a donated
# argument produced by ``*.take_*()`` / ``*.donate_*()`` has been
# detached from live state by its owner before dispatch
_HANDOFF_PREFIXES = ("take_", "donate_", "detach_")

_SYNC_METHODS = {"item", "block_until_ready", "numpy", "tolist"}
_NUMPY_SYNCS = {"asarray", "array"}
_CLOCK_CALLS = {"time", "perf_counter", "monotonic", "process_time",
                "time_ns", "perf_counter_ns", "now", "utcnow", "today"}


def _body_walk(fi: FunctionInfo) -> Iterator[ast.AST]:
    """Walk this function's body without entering nested functions."""
    if isinstance(fi.node, ast.Lambda):
        roots: Sequence[ast.AST] = [fi.node.body]
    elif isinstance(fi.node, ast.Module):
        roots = []                                  # module scope: skip
    else:
        roots = fi.node.body
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _finding(fi: FunctionInfo, node: ast.AST, rule: str, msg: str
             ) -> Finding:
    line = getattr(node, "lineno", fi.lineno)
    return Finding(rule=rule, path=fi.module.relpath, line=line,
                   func=fi.qualname, message=msg,
                   source=fi.module.line(line))


def _is_numpy_alias(fi: FunctionInfo, name: str) -> bool:
    target = fi.module.module_aliases.get(name)
    return target == "numpy" or (target or "").startswith("numpy.")


def _param_names(fi: FunctionInfo) -> set:
    node = fi.node
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
        return set()
    a = node.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    for extra in (a.vararg, a.kwarg):
        if extra is not None:
            names.append(extra.arg)
    return set(names)


def _arg_mentions_param(fi: FunctionInfo, call: ast.Call) -> bool:
    params = _param_names(fi)
    if not params:
        return False
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name) and sub.id in params:
                return True
    return False


def _is_flags_module(fi: FunctionInfo, name: str) -> bool:
    """Does local name ``name`` refer to the package flag registry?"""
    target = fi.module.module_aliases.get(name, "")
    if target.endswith(".flags") or target == "flags":
        return True
    imp = fi.module.imported_names.get(name)
    return bool(imp and (imp[1] == "flags" or imp[0].endswith("flags")))


# ------------------------------------------------------------------ TRC001
def trc001_flag_read_under_trace(fi: FunctionInfo, graph: CallGraph
                                 ) -> List[Finding]:
    """Flags get_flag/get_flags in trace-reachable code.  Deliberately
    NOT flagged: ``flags.snapshot(...)`` — the snapshot call IS the
    repo's trace-boundary marker (r06 idiom).  A snapshot taken while
    tracing still resolves once per trace, but it is one batched,
    thread-safe resolve whose ``as_tuple()`` rides the decode-program-
    cache flag key, so a later set_flags invalidates the compiled
    program instead of silently serving the stale value; per-call
    get_flag reads have neither property."""
    if not fi.traced:
        return []
    out: List[Finding] = []
    for node in _body_walk(fi):
        if not isinstance(node, ast.Call):
            continue
        name = callee_name(node)
        if name is None:
            continue
        parts = name.split(".")
        tail = parts[-1]
        if tail not in ("get_flag", "get_flags"):
            continue
        ok = False
        if len(parts) == 1:
            imp = fi.module.imported_names.get(tail)
            ok = bool(imp and imp[0].endswith("flags"))
        elif len(parts) == 2:
            ok = _is_flags_module(fi, parts[0])
        if ok:
            out.append(_finding(
                fi, node, "TRC001",
                f"registry read {name}(...) in trace-reachable code — the "
                "value is baked in at trace time and bypasses the "
                "program-cache flag key; resolve a flags.snapshot() at "
                "the trace boundary and thread it through"))
    return out


# ------------------------------------------------------------------ TRC002
def trc002_host_sync(fi: FunctionInfo, graph: CallGraph) -> List[Finding]:
    if not (fi.traced or fi.hotpath):
        return []
    ctx = ("traced function" if fi.traced
           else "declared hot path (tracecheck: hotpath)")
    out: List[Finding] = []
    for node in _body_walk(fi):
        if not isinstance(node, ast.Call):
            continue
        # x.item() / x.block_until_ready() / x.numpy() / x.tolist()
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SYNC_METHODS and not node.args:
            out.append(_finding(
                fi, node, "TRC002",
                f".{node.func.attr}() host sync in {ctx} — stalls the "
                "dispatch pipeline (and fails on traced values); keep "
                "values on device or pull them at an explicit sync point"))
            continue
        name = callee_name(node)
        if name is None:
            continue
        parts = name.split(".")
        tail = parts[-1]
        if tail == "device_get" and len(parts) >= 2:
            out.append(_finding(
                fi, node, "TRC002",
                f"jax.device_get in {ctx} — host transfer on the hot "
                "path; move it behind the metrics/sync boundary"))
        elif len(parts) == 2 and tail in _NUMPY_SYNCS and \
                _is_numpy_alias(fi, parts[0]) and \
                (fi.hotpath or _arg_mentions_param(fi, node)):
            # in traced code, np.asarray of LOCAL host data is ordinary
            # trace-time constant building; only values flowing in
            # through the traced signature can be tracers
            out.append(_finding(
                fi, node, "TRC002",
                f"{name}(...) in {ctx} — forces a device->host copy "
                "(and fails on traced values); use jnp, or sync "
                "explicitly where staleness is acceptable"))
        elif len(parts) == 1 and tail == "float" and fi.hotpath and \
                node.args and not isinstance(node.args[0], ast.Constant):
            # hotpath-only: in traced code float()/int() usually digest
            # STATIC python args (axes, shapes) — the tracer-concretizing
            # cases there are covered by TRC006 / the runtime error
            out.append(_finding(
                fi, node, "TRC002",
                f"{tail}(...) in {ctx} — blocks on the device value; "
                "pull metrics on the metrics_every/sync() cadence "
                "instead"))
    return out


# ------------------------------------------------------------------ TRC003
def _attr_chain(node: ast.AST) -> Optional[str]:
    return _dotted(node)


def _mentions_self_state(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in ("self", "cls"):
            return True
    return False


def _is_handoff_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = callee_name(node)
    if not name:
        return False
    tail = name.rsplit(".", 1)[-1]
    return tail.startswith(_HANDOFF_PREFIXES)


def trc003_donated_use(fi: FunctionInfo, graph: CallGraph,
                       donors) -> List[Finding]:
    """``donors``: resolver ``(fi, call) -> Optional[Tuple[int, ...]]``
    giving donated positional indices for a call site.  Applies to host
    code too — donation hazards live OUTSIDE the traced function.

    The reuse scan is block-structured: "after the call" means the rest
    of the call's own block plus the continuations of its enclosing
    blocks — never a sibling ``elif``/``else`` branch (those are
    mutually exclusive with the donating dispatch)."""
    out: List[Finding] = []
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return out

    def check_call(call: ast.Call, successors: List[ast.stmt],
                   own_stmt: ast.stmt) -> None:
        pos = donors(fi, call)
        if not pos:
            return
        for p in pos:
            if p >= len(call.args):
                continue
            arg = call.args[p]
            if isinstance(arg, ast.Starred):
                continue
            chain = _attr_chain(arg)
            if chain is not None:
                f = _check_chain_reuse(fi, successors, own_stmt, chain)
                if f is not None:
                    out.append(f)
            elif _is_handoff_call(arg):
                continue            # explicit ownership transfer
            elif _mentions_self_state(arg):
                line = arg.lineno
                out.append(Finding(
                    rule="TRC003", path=fi.module.relpath, line=line,
                    func=fi.qualname, source=fi.module.line(line),
                    message="donated argument is a live view of "
                            "object state — after dispatch the "
                            "donated buffers are invalid but the "
                            "object still references them (stale on "
                            "error paths); detach ownership first "
                            "via a take_*/donate_* helper"))

    def scan_block(stmts: List[ast.stmt],
                   continuation: List[ast.stmt]) -> None:
        for i, stmt in enumerate(stmts):
            successors = stmts[i + 1:] + continuation
            for call in _header_calls(stmt):
                check_call(call, successors, stmt)
            for sub in _sub_blocks(stmt):
                scan_block(sub, successors)

    scan_block(list(fi.node.body), [])
    return out


def _sub_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return []
    blocks = []
    for field_name in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, field_name, None)
        if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
            blocks.append(sub)
    for h in getattr(stmt, "handlers", []) or []:
        blocks.append(h.body)
    return blocks


def _header_calls(stmt: ast.stmt) -> List[ast.Call]:
    """Calls evaluated by this statement itself — its expressions, not
    its nested blocks (those are scanned with their own successor
    lists)."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return []                   # a nested def's calls run later
    nested = {id(s) for block in _sub_blocks(stmt) for s in block}
    out: List[ast.Call] = []
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if id(node) in nested or isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _flatten_statements(body: List[ast.stmt]) -> List[ast.stmt]:
    """Statement list in source order, descending into compound bodies
    (but not nested function defs)."""
    out: List[ast.stmt] = []
    for stmt in body:
        out.append(stmt)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for field_name in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field_name, None)
            if isinstance(sub, list):
                out.extend(_flatten_statements(
                    [s for s in sub if isinstance(s, ast.stmt)]))
        for h in getattr(stmt, "handlers", []) or []:
            out.extend(_flatten_statements(h.body))
    return out


def _assigned_chains(stmt: ast.stmt) -> List[str]:
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.For):
        targets = [stmt.target]
    out: List[str] = []
    for t in targets:
        for el in ([t] if not isinstance(t, (ast.Tuple, ast.List))
                   else list(t.elts)):
            c = _attr_chain(el)
            if c is not None:
                out.append(c)
    return out


def _check_chain_reuse(fi: FunctionInfo, successors: List[ast.stmt],
                       call_stmt: ast.stmt, chain: str
                       ) -> Optional[Finding]:
    """A Name/attribute chain passed at a donated position: flag the
    first Load of that chain after the donating statement, unless the
    chain is rebound first (including by the donating statement itself —
    the sanctioned ``x = step(x, ...)`` shape)."""
    if _assigned_in(call_stmt, chain):
        return None
    for stmt in successors:
        hit = _loads_chain(stmt, chain)
        if hit is not None:
            line = getattr(hit, "lineno", stmt.lineno)
            return Finding(
                rule="TRC003", path=fi.module.relpath, line=line,
                func=fi.qualname, source=fi.module.line(line),
                message=f"'{chain}' was donated to a jit(donate_argnums) "
                        "call and is read again before being rebound — "
                        "the buffer no longer exists after dispatch")
        if _assigned_in(stmt, chain):
            return None
    return None


def _assigned_in(stmt: ast.stmt, chain: str) -> bool:
    return any(c == chain for c in _assigned_chains(stmt))


def _loads_chain(stmt: ast.stmt, chain: str) -> Optional[ast.AST]:
    assigned = set(_assigned_chains(stmt))
    for node in ast.walk(stmt):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        c = _attr_chain(node)
        if c == chain and c not in assigned and \
                isinstance(getattr(node, "ctx", ast.Load()), ast.Load):
            return node
    return None


# ------------------------------------------------------------------ TRC004
def trc004_unstable_jit(fi: FunctionInfo, graph: CallGraph
                        ) -> List[Finding]:
    """Host-side rule: jit admissions that defeat jax's per-callable
    cache — jit inside a loop, jit of a lambda, jit immediately
    invoked."""
    out: List[Finding] = []
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return out

    def is_jit_call(node: ast.Call) -> bool:
        name = callee_name(node)
        if name is None:
            return False
        tail = name.rsplit(".", 1)[-1]
        return tail in ("jit", "jit_fn")

    # walk with loop-depth tracking
    def walk(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            child_in_loop = in_loop or isinstance(
                child, (ast.For, ast.While, ast.AsyncFor))
            if isinstance(child, ast.Call):
                if is_jit_call(child):
                    if in_loop:
                        out.append(_finding(
                            fi, child, "TRC004",
                            "jax.jit(...) inside a loop — every "
                            "iteration admits a fresh callable and "
                            "retraces; hoist the jit or key it through "
                            "the decode program cache"))
                    elif child.args and isinstance(child.args[0],
                                                   ast.Lambda):
                        out.append(_finding(
                            fi, child, "TRC004",
                            "jax.jit of a lambda built per call — jit "
                            "caches per callable object, so each fresh "
                            "closure recompiles; define the function "
                            "once or cache the jitted result"))
                elif isinstance(child.func, ast.Call) and \
                        is_jit_call(child.func):
                    out.append(_finding(
                        fi, child, "TRC004",
                        "jax.jit(f)(...) immediately invoked — the "
                        "compiled program is discarded and rebuilt on "
                        "every call; bind the jitted callable once"))
            walk(child, child_in_loop)

    walk(fi.node, False)
    return out


# ------------------------------------------------------------------ TRC005
def trc005_impure_time_rng(fi: FunctionInfo, graph: CallGraph
                           ) -> List[Finding]:
    if not fi.traced:
        return []
    out: List[Finding] = []
    for node in _body_walk(fi):
        if not isinstance(node, ast.Call):
            continue
        name = callee_name(node)
        if name is None:
            continue
        parts = name.split(".")
        if len(parts) < 2:
            continue
        root, tail = parts[0], parts[-1]
        root_target = fi.module.module_aliases.get(root, "")
        if root_target in ("time", "datetime") and tail in _CLOCK_CALLS:
            out.append(_finding(
                fi, node, "TRC005",
                f"{name}() under trace — evaluated once at trace time "
                "and baked into the compiled program; pass times in as "
                "arguments"))
        elif root_target == "random" or \
                (name.startswith("random.") and root_target == "random"):
            out.append(_finding(
                fi, node, "TRC005",
                f"stdlib {name}() under trace — one sample frozen at "
                "trace time; use jax.random with a traced key"))
        elif len(parts) >= 3 and parts[1] == "random" and \
                _is_numpy_alias(fi, root):
            out.append(_finding(
                fi, node, "TRC005",
                f"{name}() under trace — numpy RNG runs at trace time "
                "only (same values every call); use jax.random with a "
                "traced key"))
    return out


# ------------------------------------------------------------------ TRC006
def _test_has_tracer_guard(test: ast.expr) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            name = callee_name(node)
            if name and name.rsplit(".", 1)[-1] == "isinstance":
                return True
    return False


# trace-STATIC jnp predicates: dtype/shape/rank queries return concrete
# python values even on tracers — branching on them is fine.
# lax.axis_size is a static mesh-shape query (NOT axis_index, which
# returns a tracer).
_STATIC_JNP = {"shape", "ndim", "size", "result_type", "dtype",
               "iscomplexobj", "isrealobj", "issubdtype", "isdtype",
               "axis_size"}
# value-producing reductions commonly branched on: x.any(), x.sum() > 0
_VALUE_METHODS = {"any", "all", "sum", "max", "min", "mean", "prod"}
# concretizers: int(x)/float(x)/bool(x) yield host values (or raise at
# trace time) — their results are NOT tracers, so they clear taint
_CONCRETIZERS = {"int", "float", "bool"}


def _is_identity_test(test: ast.expr) -> bool:
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)


def _tensorish(fi: FunctionInfo, node: ast.expr,
               tainted: set) -> Optional[str]:
    """Does this expression compute on a jnp/lax value or a locally
    jnp-tainted name in a way that forces concretization when branched
    on?  Returns a short description or None.

    Deliberately NOT tensorish: ``x.ndim``/``x.shape`` style attribute
    reads (static under trace), ``x is None`` identity tests, dict/pytree
    container method calls like ``state.get(k)``, and anything passed
    through int()/float()/bool() (already concrete)."""
    if _is_identity_test(node):
        return None
    # parent map so `x.anything` (attribute read on a tainted name) can
    # be told apart from `x`, `x[0]`, `x + 1` (all concretizing)
    parent: dict = {}
    stack: List[ast.AST] = [node]
    order: List[ast.AST] = []
    while stack:
        sub = stack.pop()
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        order.append(sub)
        for child in ast.iter_child_nodes(sub):
            parent[id(child)] = sub
            stack.append(child)
    skip_subtrees: set = set()
    for sub in order:
        if isinstance(sub, ast.Call):
            if _under_skipped(sub, parent, skip_subtrees):
                continue
            name = callee_name(sub)
            if name:
                tail = name.rsplit(".", 1)[-1]
                if tail in _STATIC_JNP or tail in _CONCRETIZERS:
                    skip_subtrees.add(id(sub))
                    continue
                root = name.split(".")[0]
                target = fi.module.module_aliases.get(root, "")
                if target in ("jax.numpy", "jax.lax") or \
                        target.startswith("jax.numpy.") or \
                        name.startswith(("jnp.", "lax.", "jax.numpy.",
                                         "jax.lax.")):
                    return name
            if isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _VALUE_METHODS:
                base = _dotted(sub.func.value)
                if base is not None and base.split(".")[0] in tainted:
                    return f"{base}.{sub.func.attr}()"
    for sub in order:
        if not (isinstance(sub, ast.Name) and sub.id in tainted):
            continue
        if _under_skipped(sub, parent, skip_subtrees):
            continue
        p = parent.get(id(sub))
        if isinstance(p, ast.Attribute):
            continue                # x.ndim / state.get(...) — static
        return sub.id
    return None


def _under_skipped(node: ast.AST, parent: dict, skipped: set) -> bool:
    cur = node
    while cur is not None:
        if id(cur) in skipped:
            return True
        cur = parent.get(id(cur))
    return False


def _is_observability_name(fi: FunctionInfo, name: str) -> bool:
    """Is local name ``name`` imported from the observability package
    (``from .. import observability as obs`` / ``from ..observability
    import span``)?"""
    target = fi.module.module_aliases.get(name, "")
    if target.endswith("observability") or ".observability." in target:
        return True
    imp = fi.module.imported_names.get(name)
    return bool(imp and "observability" in imp[0])


def _module_imports_observability(fi: FunctionInfo) -> bool:
    for target in fi.module.module_aliases.values():
        if target.endswith("observability") or ".observability." in target:
            return True
    for modname, _orig in fi.module.imported_names.values():
        if "observability" in modname:
            return True
    return False


# instrument/tracer write methods distinctive enough to flag by name —
# but only in modules that import the observability package, so e.g. a
# quantization observer's ``.observe()`` never false-positives
_TELEMETRY_METHODS = {"inc", "dec", "observe", "span", "event"}

# the sanctioned hot-path aggregation idiom (like take_* for TRC003):
# batching a step's gauge/counter writes into one enabled-guarded
# ``_observe_*`` helper is the annotation — the name is the pragma
_OBSERVE_PREFIX = "_observe_"


def _telemetry_writes(fi: FunctionInfo) -> List:
    """Direct telemetry write call sites in this function's body:
    ``[(node, dotted_name), ...]``."""
    obs_imported = _module_imports_observability(fi)
    out = []
    for node in _body_walk(fi):
        if not isinstance(node, ast.Call):
            continue
        name = callee_name(node)
        if name is None:
            continue
        parts = name.split(".")
        if len(parts) == 1:
            if _is_observability_name(fi, parts[0]):
                out.append((node, name))
        elif _is_observability_name(fi, parts[0]):
            out.append((node, name))
        elif obs_imported and parts[-1] in _TELEMETRY_METHODS:
            out.append((node, name))
    return out


def trc007_telemetry_under_trace(fi: FunctionInfo, graph: CallGraph
                                 ) -> List[Finding]:
    """Telemetry is host-side only. In TRACE-REACHABLE code a registry/
    tracer write either fails on tracers or fires once at trace time and
    silently freezes — record at the dispatch boundary instead. In
    declared ``# tracecheck: hotpath`` code a telemetry write is legal
    but costs the path it observes, so it must carry an explicit
    ``# tracecheck: disable=TRC007`` pragma with a reason; the scan
    also reaches ONE call level into same-module helpers (batching a
    step's writes into an enabled-guarded ``_observe_*`` helper is the
    sanctioned idiom and exempt by name)."""
    out: List[Finding] = []
    if fi.traced:
        for node, name in _telemetry_writes(fi):
            out.append(_finding(
                fi, node, "TRC007",
                f"telemetry write {name}(...) in trace-reachable code — "
                "the metrics registry and span tracer are host-side "
                "only (a write here fires once at trace time and "
                "freezes, or fails on a tracer); record at the dispatch "
                "boundary instead"))
        return out
    if not fi.hotpath:
        return []
    for node, name in _telemetry_writes(fi):
        out.append(_finding(
            fi, node, "TRC007",
            f"telemetry write {name}(...) on a declared hot path — "
            "acknowledge the per-step host cost with an inline "
            "`# tracecheck: disable=TRC007` pragma and a reason"))
    # one-level helper reach: a hot path routing writes through a plain
    # same-module helper doesn't escape the annotation contract
    for node in _body_walk(fi):
        if not isinstance(node, ast.Call):
            continue
        cname = callee_name(node)
        if cname is None or \
                cname.rsplit(".", 1)[-1].startswith(_OBSERVE_PREFIX):
            continue
        for callee in graph.resolve_call(fi, node):
            if callee.module is not fi.module or callee.hotpath \
                    or callee.traced:
                continue        # other modules / directly-scanned defs
            helper = callee.qualname.rsplit(".", 1)[-1]
            if helper.startswith(_OBSERVE_PREFIX):
                continue
            for wnode, wname in _telemetry_writes(callee):
                out.append(_finding(
                    callee, wnode, "TRC007",
                    f"telemetry write {wname}(...) reached one call from "
                    f"hot path '{fi.qualname}' — pragma it with a "
                    "reason, or batch it into an `_observe_*` helper"))
    return out


def trc006_tensor_control_flow(fi: FunctionInfo, graph: CallGraph
                               ) -> List[Finding]:
    if not fi.traced or isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    # one linear pass: taint local names assigned from jnp expressions
    tainted: set = set()
    out: List[Finding] = []
    for stmt in _flatten_statements(list(fi.node.body)):
        if isinstance(stmt, ast.Assign):
            desc = _tensorish(fi, stmt.value, tainted)
            for c in _assigned_chains(stmt):
                if "." not in c:
                    (tainted.add(c) if desc else tainted.discard(c))
        if isinstance(stmt, (ast.If, ast.While)):
            if _test_has_tracer_guard(stmt.test):
                continue            # isinstance(x, Tracer)-guarded branch
            desc = _tensorish(fi, stmt.test, tainted)
            if desc is not None:
                kind = "while" if isinstance(stmt, ast.While) else "if"
                out.append(Finding(
                    rule="TRC006", path=fi.module.relpath,
                    line=stmt.lineno, func=fi.qualname,
                    source=fi.module.line(stmt.lineno),
                    message=f"Python `{kind}` on tensor-valued "
                            f"expression ({desc}) in traced code — "
                            "concretizes a tracer; use jnp.where/"
                            "lax.cond, or guard the eager branch with "
                            "isinstance(x, jax.core.Tracer)"))
    return out
