"""Orchestration: parse a package, build the graph, run the rules.

``analyze_package`` is the single entry point the CLI and the tier-1
test share.  Pure AST — the analyzed package is never imported, so the
analyzer runs in milliseconds-per-file on CPU with no jax involved.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .callgraph import (CallGraph, FunctionInfo, ModuleInfo, index_module,
                        mark_roots_from_wrapper_calls)
from .donors import ModuleDonors
from .findings import (Finding, dedupe_findings, hotpath_lines,
                       parse_pragmas, suppressed)
from . import rules as R


@dataclass
class AnalyzerConfig:
    """Tuning knobs.  ``traced_module_patterns``: relpath substrings whose
    module-level functions are treated as trace roots even without an
    explicit jit wrapper in view — the op/kernel libraries whose contract
    is "callable under jit" (model forwards reach them through dynamic
    dispatch no static analyzer can follow)."""
    traced_module_patterns: Tuple[str, ...] = (
        "/kernels/", "/nn/functional", "/ops/", "/incubate/nn/",
    )
    exclude_patterns: Tuple[str, ...] = ()
    rules: Tuple[str, ...] = ("TRC001", "TRC002", "TRC003", "TRC004",
                              "TRC005", "TRC006", "TRC007")


@dataclass
class AnalysisResult:
    findings: List[Finding]                  # post-pragma, pre-baseline
    suppressed: List[Finding]                # pragma-silenced
    n_files: int = 0
    n_functions: int = 0
    n_traced: int = 0
    errors: List[str] = field(default_factory=list)


def _iter_py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


@dataclass
class ParsedPackage:
    """One parsed package: the ast.parse output the rule suites share.
    Parsing dominates analyzer wall clock, so the unified CLI
    (tools/analyze.py) parses once and hands the same ParsedPackage to
    tracecheck AND meshcheck."""
    package: str
    modules: Dict[str, ModuleInfo]
    errors: List[str] = field(default_factory=list)
    n_files: int = 0

    def filtered(self, exclude_patterns: Tuple[str, ...]
                 ) -> "ParsedPackage":
        """A view with this exclude set applied — a shared parse may
        have been built with a different (or no) one, and both suites'
        entry paths must agree."""
        if not exclude_patterns:
            return self
        kept = {mp: m for mp, m in self.modules.items()
                if not any(p in m.relpath for p in exclude_patterns)}
        return ParsedPackage(self.package, kept, list(self.errors),
                             len(kept))


def parse_package(package_path: str,
                  exclude_patterns: Tuple[str, ...] = ()) -> ParsedPackage:
    """Parse every ``.py`` under ``package_path`` (a package directory or
    a single file).  Paths are relative to the package's parent,
    posix-style ('paddle_tpu/nn/functional.py')."""
    package_path = os.path.abspath(package_path)
    if os.path.isfile(package_path):
        parent = os.path.dirname(os.path.dirname(package_path))
        files = [package_path]
        package = os.path.basename(os.path.dirname(package_path))
    else:
        parent = os.path.dirname(package_path)
        files = list(_iter_py_files(package_path))
        package = os.path.basename(package_path)

    parsed = ParsedPackage(package=package, modules={})
    for path in files:
        rel = os.path.relpath(path, parent).replace(os.sep, "/")
        if any(p in rel for p in exclude_patterns):
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            mod = index_module(rel, source, package)
        except (SyntaxError, UnicodeDecodeError) as e:
            parsed.errors.append(f"{rel}: {e}")
            continue
        parsed.modules[_modpath(rel)] = mod
        parsed.n_files += 1
    return parsed


def analyze_package(package_path: str,
                    config: Optional[AnalyzerConfig] = None,
                    parsed: Optional[ParsedPackage] = None
                    ) -> AnalysisResult:
    """Analyze every ``.py`` under ``package_path`` (a package directory
    or a single file).  Paths in findings are relative to the package's
    parent, posix-style ('paddle_tpu/nn/functional.py').  ``parsed``
    reuses an existing parse (the root/traced flags this pass sets on it
    are monotone and idempotent, so re-analysis is stable)."""
    config = config or AnalyzerConfig()
    if parsed is None:
        parsed = parse_package(package_path, config.exclude_patterns)
    else:
        parsed = parsed.filtered(config.exclude_patterns)
    modules = parsed.modules

    result = AnalysisResult(findings=[], suppressed=[])
    result.errors = list(parsed.errors)
    result.n_files = parsed.n_files

    graph = CallGraph(modules, parsed.package)

    # roots: wrapper calls + decorators (set during indexing) + traced
    # module patterns + hotpath markers
    for mod in modules.values():
        mark_roots_from_wrapper_calls(mod)
        hot = hotpath_lines(mod.source_lines)
        in_traced_module = any(p in "/" + mod.relpath
                               for p in config.traced_module_patterns)
        for fi in mod.functions.values():
            if not fi.qualname:
                continue
            if in_traced_module and fi.parent is None and \
                    not isinstance(fi.node, ast.Lambda):
                fi.trace_root = True
            if fi.lineno in hot:
                fi.hotpath = True
    graph.propagate_traced()

    donors_by_mod = {mp: ModuleDonors(mod) for mp, mod in modules.items()}

    findings: List[Finding] = []
    for mp, mod in modules.items():
        donors = donors_by_mod[mp]

        def donor_resolver(fi: FunctionInfo, call):
            return donors.donated_positions(fi, call)

        pragmas = parse_pragmas(mod.source_lines)
        for fi in mod.functions.values():
            result.n_functions += 1
            if fi.traced:
                result.n_traced += 1
            batch: List[Finding] = []
            if "TRC001" in config.rules:
                batch += R.trc001_flag_read_under_trace(fi, graph)
            if "TRC002" in config.rules:
                batch += R.trc002_host_sync(fi, graph)
            if "TRC003" in config.rules:
                batch += R.trc003_donated_use(fi, graph, donor_resolver)
            if "TRC004" in config.rules:
                batch += R.trc004_unstable_jit(fi, graph)
            if "TRC005" in config.rules:
                batch += R.trc005_impure_time_rng(fi, graph)
            if "TRC006" in config.rules:
                batch += R.trc006_tensor_control_flow(fi, graph)
            if "TRC007" in config.rules:
                batch += R.trc007_telemetry_under_trace(fi, graph)
            for f in batch:
                (result.suppressed if suppressed(f, pragmas)
                 else findings).append(f)

    result.findings = dedupe_findings(findings)
    return result


def _modpath(rel: str) -> str:
    p = rel[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")
