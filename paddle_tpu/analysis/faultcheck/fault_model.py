"""The recovery model faultcheck reasons over (pure AST, shared parse).

Four questions drive the FLT rules:

1. **Where are the recovery seams?**  A ``try`` whose handler routes the
   failure through recovery — a call whose name matches the recovery
   vocabulary (``_recover*``/``_lose_*``/``_to_replay_form``/
   ``export_requests``) or an ownership-handoff prefix (``take_*``/
   ``install_*``/``donate_*``/``detach_*``), directly or one resolved
   call level down.  Functions called from a seam's ``try`` body (and
   their call-graph closure) are *recovery-covered*: a donated dispatch
   there has a catcher that can replay from host state.

2. **Which calls dispatch donated, handoff-detached state?**  Reuses
   tracecheck's module-scoped donor pass (``jax.jit(donate_argnums)``
   propagated through names/attrs/returns/partials/``cache.get``
   builders); faultcheck additionally asks whether the donated argument
   was produced by a ``take_*``-style handoff — that is the state a
   failed dispatch strands.

3. **Where are fault-injection sites checked, and what do metric
   registrations declare?**  ``faults.site(...)`` handles (class attrs
   and locals) and their ``.check()`` call sites feed FLT002; registry
   ``counter``/``gauge``/``histogram`` registrations (including one
   level through the pre-bound-helper idiom) feed FLT005.

4. **What is replay state?**  Classes named in the signatures of the
   replay seam functions (``_to_replay_form``/``export_requests``/
   ``inject_request``) plus ``Request`` itself; FLT003 polices stores
   into their fields.

Everything here is READ-ONLY over the shared :class:`ModuleInfo`
objects (the donor pass re-derives the same idempotent fixpoint
tracecheck computes), so running faultcheck never changes what the
other suites report on the same parse, in either order.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..tracecheck.callgraph import (CallGraph, FunctionInfo, ModuleInfo,
                                    _dotted, callee_name)
from ..tracecheck.donors import ModuleDonors
from ..tracecheck.rules import _body_walk

# ownership-handoff prefixes (the TRC003 vocabulary): a donated argument
# built by one of these has been detached from live object state
HANDOFF_PREFIXES = ("take_", "install_", "donate_", "detach_")

# recovery-routing vocabulary: a handler calling one of these absorbs
# the failure into the replay machinery instead of letting state rot
_RECOVERY_NAME = re.compile(
    r"^(recover|re_?route|lose_|to_replay_form$|export_requests$|"
    r"harvest|rebuild_pool$|finalize$)")

_REGISTRY_METHODS = ("counter", "gauge", "histogram")


def routes_recovery(tail: str) -> bool:
    """Does a call with this terminal name route a caught failure into
    recovery?"""
    return bool(_RECOVERY_NAME.match(tail.lstrip("_"))) or \
        tail.startswith(HANDOFF_PREFIXES)


@dataclass
class RegSite:
    """One metric-family registration: ``r.counter("name", ..., labels=
    (...))`` — or a call into a one-registration helper that threads its
    first parameter through as the family name (the pre-bound telemetry
    class idiom)."""
    call: ast.Call
    fi: FunctionInfo
    name: str                        # the family name literal
    kind: str                        # counter / gauge / histogram
    labels: Optional[Tuple[str, ...]]  # None = not statically known
    buckets_sig: Optional[str]       # histogram layout signature
    replica_scoped: bool             # registered from per-replica code

    def schema(self) -> Tuple:
        return (self.kind, self.labels, self.buckets_sig)


@dataclass
class FaultContext:
    graph: CallGraph
    # id(fi) -> donated-position resolver results live on demand via
    # the per-module donor passes
    donors: Dict[str, ModuleDonors]
    covered: Set[int]                 # id(fi): recovery-covered closure
    routing_trys: Dict[int, List[ast.Try]]   # id(fi) -> seam trys in fi
    recovery_reach: Set[int]          # id(fi): reachable FROM recovery
    site_attrs: Dict[str, Set[Tuple[str, str]]]   # modpath -> (cls, chain)
    site_locals: Dict[str, Set[Tuple[str, str]]]  # modpath -> (qualname, nm)
    reg_sites: Dict[int, List[RegSite]]           # id(fi) -> registrations
    reg_conflicts: Dict[int, str]     # id(call) -> conflict description
    replay_classes: frozenset = frozenset()
    fn_of: Dict[int, FunctionInfo] = field(default_factory=dict)
    n_registrations: int = 0


# ------------------------------------------------------------ faults vocab
def _is_faults_module_name(mod: ModuleInfo, root: str) -> bool:
    """Does local name ``root`` refer to the fault-injection module
    (``from ..testing import faults`` / ``import x.testing.faults``)?"""
    target = mod.module_aliases.get(root, "")
    if target.endswith("faults") or ".faults." in target:
        return True
    imp = mod.imported_names.get(root)
    return bool(imp and (imp[1] == "faults" or imp[0].endswith("faults")))


def _is_site_binding(mod: ModuleInfo, value: ast.AST) -> bool:
    """Is ``value`` a ``faults.site(...)`` call (any alias spelling)?"""
    if not isinstance(value, ast.Call):
        return False
    name = callee_name(value)
    if name is None:
        return False
    parts = name.split(".")
    if parts[-1] != "site":
        return False
    if len(parts) == 1:
        imp = mod.imported_names.get("site")
        if imp and imp[0].endswith("faults"):
            return True
        # the faults module's own helpers call site() unqualified
        return mod.relpath.endswith("testing/faults.py")
    return _is_faults_module_name(mod, parts[0])


def collect_fault_handles(mod: ModuleInfo
                          ) -> Tuple[Set[Tuple[str, str]],
                                     Set[Tuple[str, str]]]:
    """(attr handles, local handles) bound from ``faults.site(...)`` in
    this module: ``self._f_x = faults.site("...")`` per class, and
    ``_fault = faults.site("...")`` per function."""
    attrs: Set[Tuple[str, str]] = set()
    locals_: Set[Tuple[str, str]] = set()
    for fi in mod.functions.values():
        if isinstance(fi.node, (ast.Module, ast.Lambda)):
            continue
        for stmt in _body_walk(fi):
            if not isinstance(stmt, ast.Assign):
                continue
            if not _is_site_binding(mod, stmt.value):
                continue
            for t in stmt.targets:
                chain = _dotted(t)
                if chain is None:
                    continue
                if chain.startswith(("self.", "cls.")) and fi.cls:
                    attrs.add((fi.cls, chain))
                elif "." not in chain:
                    locals_.add((fi.qualname, chain))
    # module-scope handles (`_F = faults.site(...)` at top level) bind
    # under the '' scope every function's lookup chain falls back to;
    # walk top-level statements only, never into def/class bodies
    stack = list(mod.tree.body)
    while stack:
        stmt = stack.pop()
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(stmt, ast.Assign) and \
                _is_site_binding(mod, stmt.value):
            for t in stmt.targets:
                chain = _dotted(t)
                if chain is not None and "." not in chain:
                    locals_.add(("", chain))
        stack.extend(ast.iter_child_nodes(stmt))
    return attrs, locals_


def is_fault_check(fi: FunctionInfo, call: ast.Call,
                   ctx: "FaultContext") -> bool:
    """Is this call a fault-site ``check()`` — on a bound handle or the
    module-level ``faults.check("site", ...)`` convenience?"""
    name = callee_name(call)
    if name is None:
        return False
    parts = name.split(".")
    if parts[-1] != "check":
        return False
    mp = ctx.graph.modpath_of(fi.module)
    if len(parts) >= 2 and _is_faults_module_name(fi.module, parts[0]):
        return True                       # faults.check("site", ...)
    chain = ".".join(parts[:-1])
    if parts[0] in ("self", "cls") and fi.cls:
        return (fi.cls, chain) in ctx.site_attrs.get(mp, ())
    if len(parts) == 1:
        return False
    scope = fi
    while scope is not None:
        if (scope.qualname, chain) in ctx.site_locals.get(mp, ()):
            return True
        scope = scope.parent
    return ("", chain) in ctx.site_locals.get(mp, ())


# --------------------------------------------------------- recovery seams
def _walk_stmts(stmts: List[ast.stmt]):
    """Pre-order walk of a statement list that PRUNES nested function
    bodies (``ast.walk`` + ``continue`` only skips the def node itself
    — its body would still be attributed to the enclosing function)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _handler_calls(t: ast.Try) -> List[ast.Call]:
    out: List[ast.Call] = []
    for h in t.handlers:
        for node in _walk_stmts(h.body):
            if isinstance(node, ast.Call):
                out.append(node)
    return out


def _try_routes_recovery(fi: FunctionInfo, t: ast.Try,
                         graph: CallGraph) -> bool:
    for call in _handler_calls(t):
        name = callee_name(call)
        if name is None:
            continue
        if routes_recovery(name.rsplit(".", 1)[-1]):
            return True
        # one resolved level: the handler delegates to a helper that
        # routes (handler -> self._absorb() -> _to_replay_form)
        for callee in graph.resolve_call(fi, call):
            for sub in callee.calls:
                sname = callee_name(sub)
                if sname and routes_recovery(sname.rsplit(".", 1)[-1]):
                    return True
    return False


def _function_trys(fi: FunctionInfo) -> List[ast.Try]:
    """Try statements of THIS function body only — a nested closure's
    try belongs to the closure's own FunctionInfo, not the enclosing
    function (attributing it outward would mint phantom seams)."""
    return [node for node in _body_walk(fi)
            if isinstance(node, ast.Try)]


def _calls_in(stmts: List[ast.stmt]) -> List[ast.Call]:
    return [node for node in _walk_stmts(stmts)
            if isinstance(node, ast.Call)]


# ------------------------------------------------------ metric registries
def _is_registry_expr(fi: FunctionInfo, node: ast.AST) -> bool:
    """Does this expression evaluate to the metrics registry —
    ``registry()`` / ``obs.registry()`` / a name bound from one in an
    enclosing scope?"""
    if isinstance(node, ast.Call):
        name = callee_name(node)
        return bool(name and name.rsplit(".", 1)[-1] == "registry")
    chain = _dotted(node)
    if chain is None or "." in chain:
        return False
    scope = fi
    while scope is not None:
        if not isinstance(scope.node, (ast.Module, ast.Lambda)):
            for stmt in ast.walk(scope.node):
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Call) and \
                        any(isinstance(t, ast.Name) and t.id == chain
                            for t in stmt.targets):
                    vn = callee_name(stmt.value)
                    if vn and vn.rsplit(".", 1)[-1] == "registry":
                        return True
        scope = scope.parent
    return False


def _resolve_label_tuple(fi: FunctionInfo, node: ast.AST
                         ) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return tuple(out)
    if isinstance(node, ast.Name):
        scope = fi
        while scope is not None:
            if not isinstance(scope.node, (ast.Module, ast.Lambda)):
                for stmt in ast.walk(scope.node):
                    if isinstance(stmt, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id == node.id
                            for t in stmt.targets):
                        return _resolve_label_tuple(scope, stmt.value)
            scope = scope.parent
    return None


def _scope_has_replica_param(fi: FunctionInfo) -> bool:
    """The per-replica scope test: this function, an enclosing scope, or
    the enclosing class's ``__init__`` takes a ``replica`` parameter."""
    scope = fi
    while scope is not None:
        node = scope.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
            if "replica" in names:
                return True
        scope = scope.parent
    if fi.cls:
        init = fi.module.functions.get(f"{fi.cls}.__init__")
        if init is not None and init is not fi:
            a = getattr(init.node, "args", None)
            if a is not None and "replica" in [
                    p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]:
                return True
    return False


def _direct_registration(fi: FunctionInfo, call: ast.Call
                         ) -> Optional[Tuple[str, str, Optional[ast.AST],
                                             Optional[str]]]:
    """(name_literal_or_param, kind, labels_node, buckets_sig) when
    ``call`` is a registry registration; name may be a parameter name
    (helper idiom) — the caller decides what to do with it."""
    if not isinstance(call.func, ast.Attribute):
        return None
    kind = call.func.attr
    if kind not in _REGISTRY_METHODS:
        return None
    if not _is_registry_expr(fi, call.func.value):
        return None
    labels_node: Optional[ast.AST] = None
    buckets_sig: Optional[str] = None
    if len(call.args) >= 3:
        labels_node = call.args[2]
    for kw in call.keywords:
        if kw.arg == "labels":
            labels_node = kw.value
        elif kw.arg == "buckets":
            buckets_sig = ast.dump(kw.value)
    if kind == "histogram" and buckets_sig is None and \
            len(call.args) >= 4:
        buckets_sig = ast.dump(call.args[3])
    if not call.args:
        return None
    first = call.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return (first.value, kind, labels_node, buckets_sig)
    if isinstance(first, ast.Name):
        return (first.id, kind, labels_node, buckets_sig)
    return None


def _helper_registration(helper: FunctionInfo
                         ) -> Optional[Tuple[str, Optional[Tuple[str, ...]],
                                             Optional[str]]]:
    """If ``helper`` is a one-registration wrapper whose first parameter
    is threaded through as the family name (the ``def c(name, help):
    return r.counter(name, help, labels=rl)`` idiom), return
    (kind, labels, buckets_sig)."""
    node = helper.node
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    a = node.args
    pos = a.posonlyargs + a.args
    if not pos:
        return None
    first_param = pos[0].arg
    regs = []
    for call in helper.calls:
        got = _direct_registration(helper, call)
        if got is not None:
            regs.append(got)
    if len(regs) != 1:
        return None
    name, kind, labels_node, buckets_sig = regs[0]
    if name != first_param:
        return None
    labels = (_resolve_label_tuple(helper, labels_node)
              if labels_node is not None else ())
    return (kind, labels, buckets_sig)


def collect_registrations(modules: Dict[str, ModuleInfo],
                          graph: CallGraph) -> Dict[int, List[RegSite]]:
    out: Dict[int, List[RegSite]] = {}
    for mod in modules.values():
        for fi in mod.functions.values():
            sites: List[RegSite] = []
            scoped = _scope_has_replica_param(fi)
            for call in fi.calls:
                got = _direct_registration(fi, call)
                if got is not None:
                    name, kind, labels_node, buckets_sig = got
                    if not (isinstance(call.args[0], ast.Constant)):
                        continue        # param-named: the helper's caller
                                        # carries the literal
                    labels = (_resolve_label_tuple(fi, labels_node)
                              if labels_node is not None else ())
                    sites.append(RegSite(call, fi, name, kind, labels,
                                         buckets_sig, scoped))
                    continue
                # one level through the pre-bound helper idiom:
                # self.x = c("family_name", "help")
                if not call.args:
                    continue
                first = call.args[0]
                if not (isinstance(first, ast.Constant)
                        and isinstance(first.value, str)):
                    continue
                for callee in graph.resolve_call(fi, call):
                    if callee.module is not mod:
                        continue
                    helper = _helper_registration(callee)
                    if helper is not None:
                        kind, labels, buckets_sig = helper
                        sites.append(RegSite(call, fi, first.value, kind,
                                             labels, buckets_sig, scoped))
                        break
            if sites:
                out[id(fi)] = sites
    return out


def _fmt_schema(site: RegSite) -> str:
    lbl = ("?" if site.labels is None
           else "{" + ", ".join(site.labels) + "}")
    extra = " (custom buckets)" if site.buckets_sig else ""
    return f"{site.kind}{lbl}{extra}"


def find_registration_conflicts(reg_sites: Dict[int, List[RegSite]]
                                ) -> Dict[int, str]:
    """id(call) -> message for every registration whose (kind, labels,
    buckets) disagrees with another registration of the same family
    name.  Unknown label sets never conflict (under-reporting beats
    false alarms in a tier-1 gate)."""
    by_name: Dict[str, List[RegSite]] = {}
    for sites in reg_sites.values():
        for s in sites:
            by_name.setdefault(s.name, []).append(s)
    conflicts: Dict[int, str] = {}
    for name, sites in by_name.items():
        known = [s for s in sites if s.labels is not None]
        schemas = {s.schema() for s in known}
        if len(schemas) <= 1:
            continue
        for s in known:
            others = sorted(
                {f"{o.fi.module.relpath}:{o.call.lineno} as "
                 f"{_fmt_schema(o)}"
                 for o in known if o.schema() != s.schema()})
            conflicts[id(s.call)] = (
                f"metric family '{name}' registered as {_fmt_schema(s)} "
                f"here but with a different schema at "
                f"{'; '.join(others)} — the registry raises on the "
                "second registration at runtime, and which replica/"
                "component wins depends on construction order")
    return conflicts


# ------------------------------------------------------- replay vocabulary
# ONE vocabulary, no drift: the replay-class scan is owned by
# statecheck's bundle-vocabulary module (statecheck generalizes it to
# the full handoff-bundle vocabulary) and re-exported here — FLT003 and
# the STC rules read the same definition, asserted by a no-drift test.
from ..statecheck.bundle_vocab import (REPLAY_SEAM_FNS as
                                       _REPLAY_SEAM_FNS,
                                       replay_class_vocabulary)


# -------------------------------------------------------------- the build
def build_context(modules: Dict[str, ModuleInfo],
                  graph: CallGraph) -> FaultContext:
    donors = {mp: ModuleDonors(mod) for mp, mod in modules.items()}

    fn_of: Dict[int, FunctionInfo] = {}
    routing_trys: Dict[int, List[ast.Try]] = {}
    covered_seeds: List[FunctionInfo] = []
    reach_seeds: List[FunctionInfo] = []
    site_attrs: Dict[str, Set[Tuple[str, str]]] = {}
    site_locals: Dict[str, Set[Tuple[str, str]]] = {}

    for mp, mod in modules.items():
        a, l = collect_fault_handles(mod)
        if a:
            site_attrs[mp] = a
        if l:
            site_locals[mp] = l
        for fi in mod.functions.values():
            fn_of[id(fi)] = fi
            if _RECOVERY_NAME.match(fi.name.lstrip("_")):
                reach_seeds.append(fi)
            trys = [t for t in _function_trys(fi)
                    if _try_routes_recovery(fi, t, graph)]
            if not trys:
                continue
            routing_trys[id(fi)] = trys
            for t in trys:
                for call in _calls_in(t.body):
                    covered_seeds.extend(graph.resolve_call(fi, call))
                for call in _handler_calls(t):
                    reach_seeds.extend(graph.resolve_call(fi, call))

    def closure(seed: List[FunctionInfo]) -> Set[int]:
        out = {id(f) for f in seed}
        work = list(seed)
        while work:
            cur = work.pop()
            for call in cur.calls:
                for callee in graph.resolve_call(cur, call):
                    if id(callee) not in out:
                        out.add(id(callee))
                        work.append(callee)
        return out

    reg_sites = collect_registrations(modules, graph)
    return FaultContext(
        graph=graph, donors=donors,
        covered=closure(covered_seeds), routing_trys=routing_trys,
        recovery_reach=closure(reach_seeds),
        site_attrs=site_attrs, site_locals=site_locals,
        reg_sites=reg_sites,
        reg_conflicts=find_registration_conflicts(reg_sites),
        replay_classes=replay_class_vocabulary(modules),
        fn_of=fn_of,
        n_registrations=sum(len(v) for v in reg_sites.values()))
