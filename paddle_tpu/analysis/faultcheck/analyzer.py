"""Orchestration: parse (or reuse a parse), build the recovery model,
run the FLT rules.

``analyze_package`` mirrors tracecheck's and meshcheck's entry points
and accepts the same :class:`ParsedPackage`, so the unified CLI
(tools/analyze.py) runs all THREE suites over ONE ast.parse pass.  The
context build is read-only over the shared ``ModuleInfo`` objects (the
donor pass re-derives tracecheck's idempotent fixpoint), so running
faultcheck never changes what the other suites report on the same
parse, in either order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..tracecheck.analyzer import ParsedPackage, parse_package
from ..tracecheck.callgraph import CallGraph
from ..tracecheck.findings import (Finding, dedupe_findings,
                                   parse_pragmas, suppressed)
from .fault_model import build_context
from . import rules as FR


@dataclass
class AnalyzerConfig:
    exclude_patterns: tuple = ()
    rules: tuple = ("FLT001", "FLT002", "FLT003", "FLT004", "FLT005",
                    "FLT006")


@dataclass
class AnalysisResult:
    findings: List[Finding]              # post-pragma, pre-baseline
    suppressed: List[Finding]            # pragma-silenced
    n_files: int = 0
    n_functions: int = 0
    n_recovery: int = 0                  # recovery-reachable functions
    n_covered: int = 0                   # recovery-covered functions
    n_registrations: int = 0             # metric-family registrations
    errors: List[str] = field(default_factory=list)


_RULE_FNS = {
    "FLT001": FR.flt001_dispatch_outside_seam,
    "FLT002": FR.flt002_check_after_mutation,
    "FLT003": FR.flt003_replay_state_purity,
    "FLT004": FR.flt004_unbounded_retry,
    "FLT005": FR.flt005_metric_label_discipline,
    "FLT006": FR.flt006_swallowed_in_recovery,
}


def analyze_package(package_path: str,
                    config: Optional[AnalyzerConfig] = None,
                    parsed: Optional[ParsedPackage] = None
                    ) -> AnalysisResult:
    config = config or AnalyzerConfig()
    if parsed is None:
        parsed = parse_package(package_path, config.exclude_patterns)
    else:
        parsed = parsed.filtered(config.exclude_patterns)

    result = AnalysisResult(findings=[], suppressed=[])
    result.errors = list(parsed.errors)
    result.n_files = parsed.n_files

    graph = CallGraph(parsed.modules, parsed.package)
    ctx = build_context(parsed.modules, graph)
    result.n_recovery = len(ctx.recovery_reach)
    result.n_covered = len(ctx.covered)
    result.n_registrations = ctx.n_registrations

    findings: List[Finding] = []
    for mod in parsed.modules.values():
        pragmas = parse_pragmas(mod.source_lines, tool="faultcheck")
        for fi in mod.functions.values():
            result.n_functions += 1
            batch: List[Finding] = []
            for code in config.rules:
                fn = _RULE_FNS.get(code)
                if fn is not None:
                    batch += fn(fi, ctx)
            for f in batch:
                (result.suppressed if suppressed(f, pragmas)
                 else findings).append(f)

    result.findings = dedupe_findings(findings)
    return result
