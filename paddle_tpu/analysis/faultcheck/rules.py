"""The FLT rule checkers.

Each rule is ``(FunctionInfo, FaultContext) -> List[Finding]`` over ONE
function body (nested defs are their own FunctionInfo).  The rules
encode the contract the r10–r14 fault-tolerance arc rests on: every
failure is either absorbed by replay-from-host-state or surfaces
loudly — so detached-state dispatches need seams, fault checks fire
before the mutation they guard, replay state stays host-pure, retries
carry budgets, and one metric family means one schema.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..tracecheck import rules as R
from ..tracecheck.callgraph import FunctionInfo, _dotted, callee_name
from ..tracecheck.findings import Finding
from .fault_model import FaultContext, _walk_stmts, is_fault_check

FAULT_RULES: Dict[str, str] = {
    "FLT001": "donated dispatch of handoff-detached state outside a "
              "recovery seam — the argument came from a take_*/detach_* "
              "handoff, so a failed dispatch leaves the owner's state "
              "dead; the dispatch must run under a try whose handler "
              "routes through take_*/install_*/_to_replay_form-style "
              "recovery (directly or via a covering caller)",
    "FLT002": "fault-site check() ordered after a state mutation it "
              "guards — an injected fire must propagate into replay "
              "recovery from a consistent state; move the check before "
              "the first store (the r14 kv_spill rule), or pragma a "
              "deliberately mid-mutation schedule point with a reason",
    "FLT003": "replay-structure field assigned from a jnp/device-"
              "producing expression — exported request/replay state "
              "must be host values (prompt, emitted tokens, cursors); "
              "a device buffer stored here dies with the pool the "
              "failure killed and the replay reads garbage",
    "FLT004": "retry/backoff loop without a FLAGS_*max_retries-style "
              "budget, deadline, or progress mark — an unbounded "
              "sleep-retry loop spins forever on a wedged backend; "
              "bound it by a flag-derived budget and fail loudly when "
              "the budget is spent",
    "FLT005": "metric-family label discipline: a family registered "
              "from per-replica code must bind the 'replica' label "
              "(two engines in one process otherwise collide on one "
              "series), and one family name must keep ONE kind/label-"
              "set/bucket-layout across every registration site",
    "FLT006": "broad except in recovery-reachable code that neither "
              "re-raises, counts a counter, nor sets a terminal "
              "status — a swallowed failure inside the recovery "
              "machinery is an invisible wedge (requests hang, drills "
              "pass vacuously)",
}

_SLEEP_TAILS = {"sleep"}

# identifiers whose presence in a retry loop's test/body marks a bound:
# flag-derived budgets, deadlines, or explicit progress marks
_BOUND_IDENT = re.compile(
    r"(retr|budget|attempt|restart|max_loss|deadline|timeout|max_wall|"
    r"progress|patience)", re.IGNORECASE)
_CLOCK_TAILS = {"time", "perf_counter", "monotonic"}

_BROAD_EXC = {"Exception", "BaseException"}

# value wrappers that yield HOST values even over device inputs: their
# result is safe to store in replay state.  The concretizer vocabulary
# and the device-value detector are OWNED by statecheck's
# bundle-vocabulary module (STC001 generalizes FLT003 to the full
# bundle vocabulary) and aliased here so the two suites cannot drift.
from ..statecheck.bundle_vocab import (BUILTIN_CONCRETIZERS as
                                       _BUILTIN_CONCRETIZERS,
                                       NP_CONCRETIZERS as
                                       _NP_CONCRETIZERS,
                                       HOST_METHODS as _HOST_METHODS,
                                       is_concretizer_call as
                                       _is_concretizer_call)


def _finding(fi: FunctionInfo, node: ast.AST, rule: str,
             msg: str) -> Finding:
    line = getattr(node, "lineno", fi.lineno)
    return Finding(rule=rule, path=fi.module.relpath, line=line,
                   func=fi.qualname, message=msg,
                   source=fi.module.line(line))


# ------------------------------------------------------------------ FLT001
def _handoff_locals(fi: FunctionInfo) -> Set[str]:
    """Local names assigned from a ``take_*``-style handoff call
    anywhere in this function."""
    out: Set[str] = set()
    for stmt in R._body_walk(fi):
        if not isinstance(stmt, ast.Assign):
            continue
        if not (isinstance(stmt.value, ast.Call)
                and R._is_handoff_call(stmt.value)):
            continue
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _in_routing_try(fi: FunctionInfo, ctx: FaultContext,
                    call: ast.Call) -> bool:
    for t in ctx.routing_trys.get(id(fi), ()):
        for node in ast.walk(t):
            if node is call:
                return True
    return False


def flt001_dispatch_outside_seam(fi: FunctionInfo, ctx: FaultContext
                                 ) -> List[Finding]:
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    mp = ctx.graph.modpath_of(fi.module)
    donors = ctx.donors.get(mp)
    if donors is None:
        return []
    handoffs = None                      # computed lazily
    out: List[Finding] = []
    for call in fi.calls:
        pos = donors.donated_positions(fi, call)
        if not pos:
            continue
        detached = False
        for p in pos:
            if p >= len(call.args):
                continue
            arg = call.args[p]
            if R._is_handoff_call(arg):
                detached = True
                break
            chain = _dotted(arg)
            if chain is not None and "." not in chain:
                if handoffs is None:
                    handoffs = _handoff_locals(fi)
                if chain in handoffs:
                    detached = True
                    break
        if not detached:
            continue
        if id(fi) in ctx.covered or _in_routing_try(fi, ctx, call):
            continue
        out.append(_finding(
            fi, call, "FLT001",
            f"donated dispatch {callee_name(call) or '<call>'}(...) of "
            "handoff-detached state outside a recovery seam — no "
            "enclosing or covering try routes the failure through "
            "take_*/install_*/_to_replay_form recovery, so a failed "
            "dispatch strands the detached state with nobody to "
            "rebuild it; wrap the drive path in a recovery seam (the "
            "serving step()/_recover_dispatch shape)"))
    return out


# ------------------------------------------------------------------ FLT002
def _store_targets(stmt: ast.stmt) -> List[Tuple[str, ast.AST]]:
    """(base chain, node) for every store target this statement writes:
    attribute chains and subscript bases (``self.x = ``,
    ``self._slots[i] = ``, ``node["host"] = ``)."""
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out: List[Tuple[str, ast.AST]] = []
    for t in targets:
        for el in ([t] if not isinstance(t, (ast.Tuple, ast.List))
                   else list(t.elts)):
            if isinstance(el, ast.Name):
                continue        # rebinding a local is a read, not a
                                # mutation (aliases rebind freely)
            base = el
            while isinstance(base, ast.Subscript):
                base = base.value
            chain = _dotted(base)
            if chain is not None:
                out.append((chain, el))
    return out


def flt002_check_after_mutation(fi: FunctionInfo, ctx: FaultContext
                                ) -> List[Finding]:
    """Scan with statement-dominance: a store taints the path; a
    handoff call (``take_*`` — the start of a fresh fail-safe region)
    clears it; a fault-site ``check()`` on a tainted path is a finding.
    Stores inside an exclusive-exit sub-block (one ending in
    return/raise/continue/break) never taint the continuation."""
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    has_check = any(is_fault_check(fi, c, ctx) for c in fi.calls)
    if not has_check:
        return []
    out: List[Finding] = []
    aliases: Set[str] = set()

    def is_state_chain(chain: str) -> bool:
        root = chain.split(".")[0]
        return root in ("self", "cls") or root in aliases

    def note_aliases(stmt: ast.stmt) -> None:
        # node = self._nodes[key]: stores through `node` mutate state
        if not isinstance(stmt, ast.Assign):
            return
        value = stmt.value
        base = value
        while isinstance(base, ast.Subscript):
            base = base.value
        chain = _dotted(base)
        if chain is None or not is_state_chain(chain):
            return
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                aliases.add(t.id)

    def exits(block: List[ast.stmt]) -> bool:
        return bool(block) and isinstance(
            block[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))

    def scan(stmts: List[ast.stmt],
             dirty: Optional[ast.stmt]) -> Optional[ast.stmt]:
        for stmt in stmts:
            header = R._header_calls(stmt)
            if any(R._is_handoff_call(c) for c in header):
                dirty = None            # fresh fail-safe region
            for call in header:
                if is_fault_check(fi, call, ctx) and dirty is not None:
                    out.append(_finding(
                        fi, call, "FLT002",
                        "fault-site check() fires AFTER a state "
                        f"mutation (line {dirty.lineno}: "
                        f"`{fi.module.line(dirty.lineno)}`) — an "
                        "injected fault here propagates into recovery "
                        "from a half-applied state; fire the check "
                        "before the first store, or pragma a "
                        "deliberately mid-mutation schedule point "
                        "with a reason"))
            note_aliases(stmt)
            stored = [n for c, n in _store_targets(stmt)
                      if is_state_chain(c)]
            if stored and dirty is None:
                dirty = stmt
            for sub in R._sub_blocks(stmt):
                sub_dirty = scan(sub, dirty)
                if sub_dirty is not None and not exits(sub):
                    dirty = dirty or sub_dirty
        return dirty

    scan(list(fi.node.body), None)
    return out


# ------------------------------------------------------------------ FLT003
# the jnp/lax/jax-rooted device-value detector is shared with STC001;
# statecheck owns it (see the concretizer import note above)
from ..statecheck.bundle_vocab import device_producing as _device_producing


def _replay_instances(fi: FunctionInfo, ctx: FaultContext) -> Set[str]:
    """Local names holding replay-structure instances in this function:
    parameters annotated with a replay class, locals constructed from
    one, and — in modules that define/import a replay class — the
    conventional ``req``/``request`` names."""
    out: Set[str] = set()
    node = fi.node
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for p in (node.args.posonlyargs + node.args.args
                  + node.args.kwonlyargs):
            ann = p.annotation
            if ann is not None and any(
                    isinstance(s, ast.Name) and s.id in ctx.replay_classes
                    for s in ast.walk(ann)):
                out.add(p.arg)
        for stmt in R._body_walk(fi):
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                vn = callee_name(stmt.value)
                if vn and vn.rsplit(".", 1)[-1] in ctx.replay_classes:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
    # conventional names count in modules that import or define a
    # replay class (serving/fleet pass Request objects through untyped
    # loops: `for req in victims:`)
    mod = fi.module
    mod_has_replay = any(
        imp[1] in ctx.replay_classes
        for imp in mod.imported_names.values())
    if not mod_has_replay:
        for sub in mod.tree.body:
            if isinstance(sub, ast.ClassDef) and \
                    sub.name in ctx.replay_classes:
                mod_has_replay = True
                break
    if mod_has_replay:
        out.update(("req", "request"))
    return out


def flt003_replay_state_purity(fi: FunctionInfo, ctx: FaultContext
                               ) -> List[Finding]:
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    insts = _replay_instances(fi, ctx)
    if not insts:
        return []
    out: List[Finding] = []
    for node in R._body_walk(fi):
        value: Optional[ast.expr] = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                chain = _dotted(t)
                if chain and "." in chain and \
                        chain.split(".")[0] in insts:
                    value = node.value
                    break
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("append", "extend", "insert") and \
                node.args:
            chain = _dotted(node.func.value)
            if chain and chain.split(".")[0] in insts:
                value = node.args[-1]
        if value is None:
            continue
        culprit = _device_producing(fi, value)
        if culprit is not None:
            out.append(_finding(
                fi, node, "FLT003",
                f"replay-structure field assigned from {culprit}(...) "
                "— exported request/replay state must be pure host "
                "values (prompt, emitted tokens, cursors); a device "
                "value stored here dies with the pool a failure kills "
                "and the replayed continuation reads garbage; "
                "concretize first (int()/np.asarray())"))
    return out


# ------------------------------------------------------------------ FLT004
def _mentions_bound(nodes: List[ast.AST]) -> bool:
    for sub in _walk_stmts(nodes):
        if isinstance(sub, ast.Name) and _BOUND_IDENT.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and \
                _BOUND_IDENT.search(sub.attr):
            return True
        if isinstance(sub, ast.Call):
            n = callee_name(sub)
            if n and n.rsplit(".", 1)[-1] in _CLOCK_TAILS:
                return True
    return False


def flt004_unbounded_retry(fi: FunctionInfo, ctx: FaultContext
                           ) -> List[Finding]:
    if isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    out: List[Finding] = []
    for stmt in R._body_walk(fi):
        if not isinstance(stmt, ast.While):
            continue
        sleeps = [c for s in stmt.body for c in _walk_calls(s)
                  if (callee_name(c) or "").rsplit(".", 1)[-1]
                  in _SLEEP_TAILS]
        if not sleeps:
            continue
        if _mentions_bound([stmt.test] + list(stmt.body)):
            continue
        out.append(_finding(
            fi, sleeps[0], "FLT004",
            "retry/backoff loop with no visible bound — nothing in the "
            "loop references a FLAGS_*max_retries-style budget, a "
            "deadline/timeout, or a progress mark, so a wedged backend "
            "spins here forever; bound the loop by a flag-derived "
            "budget (and raise loudly when it is spent) or by a "
            "deadline"))
    return out


def _walk_calls(node: ast.AST) -> Iterable[ast.Call]:
    for sub in _walk_stmts([node]):
        if isinstance(sub, ast.Call):
            yield sub


# ------------------------------------------------------------------ FLT005
def flt005_metric_label_discipline(fi: FunctionInfo, ctx: FaultContext
                                   ) -> List[Finding]:
    out: List[Finding] = []
    for site in ctx.reg_sites.get(id(fi), ()):
        conflict = ctx.reg_conflicts.get(id(site.call))
        if conflict is not None:
            out.append(_finding(fi, site.call, "FLT005", conflict))
        if site.replica_scoped and site.labels is not None and \
                "replica" not in site.labels:
            out.append(_finding(
                fi, site.call, "FLT005",
                f"metric family '{site.name}' registered from "
                "per-replica code without a 'replica' label — two "
                "engines in one process (the fleet case) collide on "
                "one series: one replica's writes pollute another's; "
                "bind .labels(replica=...) once per engine (the "
                "_EngineTelemetry idiom)"))
    return out


# ------------------------------------------------------------------ FLT006
def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True
    types = (h.type.elts if isinstance(h.type, (ast.Tuple, ast.List))
             else [h.type])
    for t in types:
        name = _dotted(t)
        if name and name.rsplit(".", 1)[-1] in _BROAD_EXC:
            return True
    return False


def _handler_absorbs_loudly(h: ast.ExceptHandler) -> bool:
    """Re-raises, counts a counter, sets a terminal status, or captures
    the exception for later handling."""
    for node in _walk_stmts(h.body):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = callee_name(node)
            if name is None:
                continue
            tail = name.rsplit(".", 1)[-1]
            if tail in ("inc", "warn", "warning", "error",
                        "exception") or \
                    tail.startswith(("_observe_", "_finalize", "_fail",
                                     "_expire", "_recover", "_lose_")):
                return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                chain = _dotted(t)
                if chain and chain.rsplit(".", 1)[-1] in ("status",
                                                          "error"):
                    return True
            # err = e: captured for later re-raise/report
            if h.name and isinstance(node.value, ast.Name) and \
                    node.value.id == h.name:
                return True
    return False


def flt006_swallowed_in_recovery(fi: FunctionInfo, ctx: FaultContext
                                 ) -> List[Finding]:
    if id(fi) not in ctx.recovery_reach or \
            isinstance(fi.node, (ast.Module, ast.Lambda)):
        return []
    out: List[Finding] = []
    for node in R._body_walk(fi):
        if not isinstance(node, ast.Try):
            continue
        for h in node.handlers:
            if not _is_broad_handler(h):
                continue
            if _handler_absorbs_loudly(h):
                continue
            out.append(_finding(
                fi, h, "FLT006",
                "broad except in recovery-reachable code swallows the "
                "failure — it neither re-raises, counts a counter, "
                "sets a terminal status, nor captures the exception "
                "for later handling; a silent wedge here makes fault "
                "drills pass vacuously while requests hang"))
    return out
