"""faultcheck — a recovery-discipline static analyzer.

tracecheck (r08) gates *trace* discipline and meshcheck (r11) gates
*collective* discipline; faultcheck gates the invariants the r10–r14
fault-tolerance arc established and every review pass since has
re-checked by hand: replay-from-host-state only works when donated
dispatches sit inside recovery seams, fault-site checks fire BEFORE the
mutation they guard, exported replay state stays host-pure, retry loops
carry budgets, and metric families keep one schema per name.  Fault
drills only exercise the schedules you arm; the lint covers every seam
on every run.

Rules (all pure AST over the shared tracecheck parse):

- **FLT001** donated dispatch of handoff-detached state (an argument
  produced by ``take_*``/``donate_*``/``detach_*``) reachable outside a
  recovery seam — no enclosing/covering ``try`` routes the failure
  through ``take_*``/``install_*``/``_to_replay_form``-style recovery,
  so a failed dispatch leaves the detached state dead with nobody to
  rebuild it (reuses tracecheck's donor call graph).
- **FLT002** fault-site ``check()`` ordered AFTER a state mutation it
  guards (the r14 kv_spill "fire BEFORE mutation" rule, via
  statement-dominance within the function): an injected fire must
  propagate into replay recovery from a consistent state, never from a
  half-applied one.
- **FLT003** replay-state purity: a field of an exported
  request/replay structure assigned from a ``jnp.``/device-producing
  expression — replay state must be host values (device buffers die
  with the pool the failure killed).
- **FLT004** retry/backoff loop without a ``FLAGS_*max_retries``-style
  bound, deadline, or progress mark — an unbounded sleep-retry loop
  spins forever on a wedged backend instead of failing loudly.
- **FLT005** metric-family label discipline: families registered from
  per-replica code must bind the ``replica`` label, and re-registration
  of one family name with mismatched label sets / kinds / bucket
  layouts (the exact r14 fleet collision class, made static).
- **FLT006** broad ``except`` in recovery-reachable code that neither
  re-raises, counts a counter, nor sets a terminal status — a swallowed
  failure inside the recovery machinery is an invisible wedge.

Findings support inline ``# faultcheck: disable=FLT00x`` pragmas (suite
-scoped: a tracecheck/meshcheck pragma never silences FLT rules) and a
checked-in baseline (tools/faultcheck_baseline.json, kept empty — the
r08/r11 precedent is fix, don't baseline); the tier-1 test gates NEW
findings only.

Run it locally::

    python tools/analyze.py                     # all three suites
    python tools/analyze.py --suite faultcheck
    python tools/analyze.py --changed-only      # git-diff-scoped
    python tools/analyze.py --format sarif      # CI annotation
"""

from ..tracecheck.findings import (Finding, fingerprint, load_baseline,
                                   subtract_baseline, write_baseline)
from .analyzer import AnalyzerConfig, AnalysisResult, analyze_package
from .rules import FAULT_RULES

__all__ = [
    "AnalyzerConfig", "AnalysisResult", "Finding", "FAULT_RULES",
    "analyze_package", "fingerprint", "load_baseline",
    "subtract_baseline", "write_baseline",
]
