"""reference: python/paddle/hub.py — torch.hub-style loading from a
LOCAL directory (source="local"). Remote github sources need network
egress, which this environment forbids — they raise with guidance."""

from __future__ import annotations

import importlib.util
import os

HUB_CONF = "hubconf.py"


def _load_local_entry(repo_dir: str):
    path = os.path.join(repo_dir, HUB_CONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {HUB_CONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _entries(mod):
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def list(repo_dir: str, source: str = "local", force_reload: bool = False):
    if source != "local":
        raise NotImplementedError(
            "paddle.hub: only source='local' is supported (no network "
            "egress on this deployment); clone the repo and pass its path")
    return _entries(_load_local_entry(repo_dir))


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False):
    if source != "local":
        raise NotImplementedError("paddle.hub: only source='local'")
    return getattr(_load_local_entry(repo_dir), model).__doc__


def load(repo_dir: str, model: str, *args, source: str = "local",
         force_reload: bool = False, **kwargs):
    if source != "local":
        raise NotImplementedError("paddle.hub: only source='local'")
    return getattr(_load_local_entry(repo_dir), model)(*args, **kwargs)
